"""Fast-lane sharded-serving smoke + plan/placement units.

The trained-model tp/dp serving suite (parity on every decode front,
per-device KV, runtime churn, subprocess warm start) lives in
tests/test_sharded_serving.py (slow lane). This module keeps tier-1
coverage of the sharded machinery cheap: a tiny UNTRAINED
token-parity smoke (argmax over random-initialized weights is
deterministic, so sharded-vs-single byte equality needs no
training), the ShardingPlan/ShardingConfig identity+validation
contracts, the mesh carve, the ReplicaSet fingerprint, and the
compile-cache mesh-mismatch named discard.
"""
import os
import pickle

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import unique_name
from paddle_tpu.core.scope import Scope
from paddle_tpu.inference import (ContinuousGenerationServer,
                                  apply_eos_sentinel)
from paddle_tpu.models import transformer as T
from paddle_tpu.models.decode_engine import (CacheConfig,
                                             ShardingConfig,
                                             place_sharded_program)

DIMS = dict(seq_len=6, max_out_len=8, d_model=16, n_heads=2,
            n_layers=1, d_inner=32, vocab=16, start_id=1, end_id=2)


def _init_scope(exe):
    """Random-initialized (untrained) weights: greedy argmax over
    them is deterministic, which is all byte-parity needs."""
    fluid.seed(3)
    scope = Scope()
    with unique_name.guard():
        _m, st, _loss = T.build_program(
            seq_len=DIMS["seq_len"], d_model=DIMS["d_model"],
            n_heads=DIMS["n_heads"], n_layers=DIMS["n_layers"],
            d_inner=DIMS["d_inner"], vocab=DIMS["vocab"],
            with_optimizer=False, dropout_rate=0.0)
    exe.run(st, scope=scope)
    return scope


class TestSmokeParity:
    def test_whole_loop_and_burst_sharded_vs_single(self):
        exe = fluid.Executor(fluid.TPUPlace(0))
        scope = _init_scope(exe)
        srcs = np.random.RandomState(5).randint(
            3, DIMS["vocab"], (4, DIMS["seq_len"])).astype(np.int64)
        with unique_name.guard():
            inc_m, _, _, inc_buf = T.build_incremental_decode_program(
                **DIMS)
        want, = exe.run(inc_m, feed={"src_ids": srcs},
                        fetch_list=[inc_buf], scope=scope)
        want = apply_eos_sentinel(np.asarray(want), DIMS["end_id"])
        # sharded whole-loop front
        with unique_name.guard():
            sh_m, _, _, sh_buf = T.build_incremental_decode_program(
                sharding=ShardingConfig(tp=2), **DIMS)
        assert place_sharded_program(sh_m, scope) > 0
        got, = exe.run(sh_m, feed={"src_ids": srcs},
                       fetch_list=[sh_buf], scope=scope)
        np.testing.assert_array_equal(
            apply_eos_sentinel(np.asarray(got), DIMS["end_id"]), want)
        # sharded slot-pool burst front
        with unique_name.guard():
            b = T.build_decode_step_program(
                n_slots=2, admit_buckets=[2], state_prefix="@fsm/",
                sharding=ShardingConfig(tp=2), **DIMS)
        with ContinuousGenerationServer(b, executor=exe,
                                        scope=scope) as srv:
            outs = [srv.submit(s) for s in srcs]
            got = np.stack([o.result(120.0) for o in outs])
        np.testing.assert_array_equal(got, want)


class TestIdentity:
    def _bundle(self, prefix, sharding=None):
        with unique_name.guard():
            return T.build_decode_step_program(
                n_slots=2, admit_buckets=[2], state_prefix=prefix,
                sharding=sharding, **DIMS)

    def test_sharded_and_dense_fingerprints_differ(self):
        from paddle_tpu.inference.runtime import server_fingerprint

        b_dense = self._bundle("@fid/")
        b_tp = self._bundle("@fid/", sharding=ShardingConfig(tp=2))
        assert b_dense.cache_token() != b_tp.cache_token()

        class _Srv:
            def __init__(self, bundle):
                self.bundle = bundle

        assert server_fingerprint(_Srv(b_dense)) != \
            server_fingerprint(_Srv(b_tp))

    def test_plan_token_separates_device_slices(self):
        import jax

        b = self._bundle("@ftk/", sharding=ShardingConfig(tp=2))
        plan = b.sharding_plan
        t0 = plan.token()
        plan.bind(jax.devices()[:2])
        t1 = plan.token()
        assert t1 != t0
        plan.bind(jax.devices()[2:4])
        assert plan.token() != t1

    def test_sharding_config_validation(self):
        with pytest.raises(ValueError, match="n_heads"):
            ShardingConfig(tp=3).validate(4, 64, 32, 64)
        with pytest.raises(ValueError, match="reserved"):
            ShardingConfig(tp=2, axis="lanes").validate(4, 64, 32, 64)
        with pytest.raises(ValueError, match="mesh_devices"):
            ContinuousGenerationServer(
                _BundleStub(), mesh_devices=[1, 2])


class _BundleStub:
    """Minimal dense bundle stand-in for the mesh_devices refusal."""
    cache = CacheConfig()
    n_slots = 1
    end_id = 1
    max_out_len = 8
    state = {}
    serves = {}
    sharding_plan = None

    def init_slot_state(self, scope):
        raise AssertionError("must refuse before state init")


class TestPlacementUnits:
    def test_plan_mesh_carve_and_bounds(self):
        import jax

        from paddle_tpu.inference.runtime import plan_mesh

        mp = plan_mesh(n_tp_models=2, tp=2, n_dp_lanes=4)
        devs = jax.devices()
        assert [d.id for d in mp.tp_slices[0]] == [devs[0].id,
                                                   devs[1].id]
        assert [d.id for d in mp.tp_slices[1]] == [devs[2].id,
                                                   devs[3].id]
        assert [d.id for d in mp.dp_devices] == [d.id
                                                 for d in devs[4:8]]
        with pytest.raises(ValueError):
            plan_mesh(n_tp_models=4, tp=2, n_dp_lanes=4)

    def test_replica_set_fingerprint_depends_on_lanes(self):
        from paddle_tpu.core.executor import Executor, TPUPlace
        from paddle_tpu.inference.runtime import (ReplicaSet,
                                                  server_fingerprint,
                                                  zoo)

        exe = Executor(TPUPlace(0))
        servers = []
        for j in range(2):
            srv, _sc = zoo.make_fc_server(f"frs{j}", 8, 16, 4,
                                          executor=exe, start=False)
            servers.append(srv)
        f2 = server_fingerprint(ReplicaSet(servers))
        f1 = server_fingerprint(ReplicaSet(servers[:1]))
        assert f2 != f1
        for s in servers:
            s.close()


class TestMeshMismatchDiscard:
    def test_mesh_mismatched_entry_is_named_discard(self, tmp_path):
        """An entry whose recorded mesh devices do not exist locally
        must be discarded with a NAMED reason before deserialization
        is even attempted — never a jaxlib crash."""
        from paddle_tpu.core import compile_cache as CC
        from paddle_tpu.flags import set_flags

        set_flags({"FLAGS_compile_cache": "rw",
                   "FLAGS_compile_cache_dir": str(tmp_path / "cc")})
        try:
            cache = CC.active_cache()
            digest = "ab" + "0" * 62
            path = cache._path(digest)
            os.makedirs(os.path.dirname(path), exist_ok=True)
            entry = {"magic": CC._MAGIC, "format": "aot",
                     "payload": b"\x00junk-not-an-executable",
                     "in_tree": None, "out_tree": None,
                     "meta": {"mesh": {"ndev": 2,
                                       "axes": [["tp", 2]],
                                       "device_ids": [98, 99]}}}
            with open(path, "wb") as f:
                pickle.dump(entry, f)
            assert cache.load_executable(digest) is None
            assert "mesh mismatch" in cache.last_discard_reason
            assert "98" in cache.last_discard_reason
        finally:
            set_flags({"FLAGS_compile_cache": "off"})
