"""Speculative draft-and-verify decoding + sampling lanes
(models/decode_engine.py DraftConfig/SamplingConfig,
inference/serving.py spec stats; ops/spec_ops.py kernels).

The invariants the r14 design must hold:

* GREEDY speculative decoding is TOKEN-EXACT vs the whole-loop
  incremental decode — the acceptance rule degenerates exactly, so
  the r10/r13 parity harness carries over: slot reuse, admission-order
  permutations, burst lengths, and the PAGED layout (the multi-position
  verify scatter must respect lane exclusivity);
* SAMPLED lanes are keyed purely on (per-request seed, position):
  bit-identical reproduction across admission-order permutations and
  repeated submission, while distinct seeds actually vary the stream
  and the modal sample sits on the model's greedy mode;
* the device-side acceptance counters have honest UNITS (emitted ==
  generated tokens, draft_steps == k * target_steps, accepted <=
  proposed);
* k=0 degenerates to the plain one-token r10 path;
* 100-request churn compiles NOTHING after warmup;
* fingerprints separate spec/sampled/plain bundles (never dedupe or
  hot-swap as the same model), and a draft prefix colliding with the
  target's params is REFUSED at build (PTA100 pair lint).
"""
import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.inference import (ContinuousGenerationServer,
                                  PagedContinuousGenerationServer,
                                  apply_eos_sentinel,
                                  count_generated_tokens)
from paddle_tpu.models.decode_engine import (CacheConfig, DraftConfig,
                                             SamplingConfig)

V, D, H, L, S, MAXT = 16, 32, 2, 1, 10, 32
DD = 16          # draft width (d16/L1 — the CLAUDE.md tiny-task tier)
K = 2            # proposals per lane per step
END_ID = 1
N_SLOTS = 4
# paged geometry sized so coverage in (k+1)-position ticks never
# exhausts and prompt entries outlive the whole workload (eviction/
# exhaustion semantics are test_paged_decode's subject — here a
# prefix entry evicted mid-test would silently turn the HIT-tier
# assertion into a miss)
BS, NB, E = 8, 20, 12


# FIXED prompt pool (the "repeated-suffix mix" the ISSUE names): 8
# memorizable sequences with planted end_id at varied positions.
# Training on random-content terminator-copy leaves BOTH tiny models
# at ~1.7 loss (measured) — they terminate correctly but their
# content tokens are noise, so draft/target agreement (= acceptance)
# sits at chance. A small fixed pool is memorizable by any capacity:
# both models converge to the SAME near-deterministic streams and the
# draft actually accepts — the regime speculative decoding exists for
# (production analogue: repeated system prompts / templated traffic).
_POOL_RNG = np.random.RandomState(5)
PROMPT_POOL = []
for _p in (1, 2, 3, 4, 6, 8, 10, 10):
    _src = _POOL_RNG.randint(3, V, (S,)).astype(np.int64)
    if _p < S:
        _src[_p:] = END_ID
    PROMPT_POOL.append(_src)
PROMPT_POOL = np.stack(PROMPT_POOL)


def _mixed_len_prompts(rng, n):
    """n draws from the fixed pool — MODEL-DRIVEN mixed output
    lengths (varied planted EOS) with high draft/target agreement."""
    return PROMPT_POOL[rng.randint(0, len(PROMPT_POOL), n)]


@pytest.fixture(scope="module")
def trained():
    """Train target (d32/L1) AND draft (d16/L1) terminator-copy
    models into ONE scope (disjoint param names via the draft_
    prefix), build the whole-loop oracle + the bundle flavors."""
    from paddle_tpu import unique_name
    from paddle_tpu.core.scope import Scope
    from paddle_tpu.models import transformer as T

    fluid.seed(0)
    scope = Scope()
    exe = fluid.Executor(fluid.TPUPlace(0))
    # ONE guard over both train builds: each creates auto-named
    # optimizer state, and resetting the counter between them would
    # hand the draft's moments the target's names in the shared scope
    with unique_name.guard():
        t_main, t_st, t_loss = T.build_program(
            seq_len=S, d_model=D, n_heads=H, n_layers=L, d_inner=64,
            vocab=V, with_optimizer=False, dropout_rate=0.0)
        with fluid.program_guard(t_main, t_st):
            fluid.optimizer.Adam(learning_rate=0.02).minimize(t_loss)
        d_main, d_st, d_loss = T.build_program(
            seq_len=S, d_model=DD, n_heads=H, n_layers=L, d_inner=32,
            vocab=V, with_optimizer=False, dropout_rate=0.0,
            name_prefix="draft_")
        with fluid.program_guard(d_main, d_st):
            fluid.optimizer.Adam(learning_rate=0.02).minimize(d_loss)
    exe.run(t_st, scope=scope)
    exe.run(d_st, scope=scope)
    rng = np.random.RandomState(7)
    for _ in range(150):
        src = _mixed_len_prompts(rng, 8)
        tgt_in = np.concatenate(
            [np.full((8, 1), 2, np.int64), src[:, :-1]], 1)
        feed = {"src_ids": src, "tgt_ids": tgt_in, "label": src}
        exe.run(t_main, feed=feed, fetch_list=[t_loss], scope=scope)
        exe.run(d_main, feed=feed, fetch_list=[d_loss], scope=scope)

    kwargs = dict(seq_len=S, max_out_len=MAXT, d_model=D, n_heads=H,
                  n_layers=L, d_inner=64, vocab=V, start_id=2,
                  end_id=END_ID)
    draft = DraftConfig(d_model=DD, n_heads=H, n_layers=L,
                        d_inner=32, k=K)
    with unique_name.guard():
        inc_m, _, _, inc_buf = T.build_incremental_decode_program(
            **kwargs)
    # admission ladder [1, N_SLOTS] (not the full power-of-two
    # ladder): halves the serve-program compile bill of the five
    # bundle flavors — this module must fit the tier-1 fast lane
    buckets = [1, N_SLOTS]
    with unique_name.guard():
        spec = T.build_decode_step_program(
            n_slots=N_SLOTS, state_prefix="@sp/", draft=draft,
            admit_buckets=buckets, **kwargs)
    with unique_name.guard():
        pspec = T.build_decode_step_program(
            n_slots=N_SLOTS, state_prefix="@pp/", draft=draft,
            admit_buckets=buckets,
            cache=CacheConfig(layout="paged", block_size=BS,
                              n_blocks=NB, n_prompt_entries=E),
            **kwargs)
    with unique_name.guard():
        # temperature 1.0 on the MEMORIZED pool task: confident
        # per-position dists make "modal sample == argmax" sound
        # over 40 draws, while the residual tail still varies long
        # generations across seeds (a 1.5 run on the noisier
        # random-content task measured near-uniform firsts and made
        # the mode assertion a coin flip)
        sampled = T.build_decode_step_program(
            n_slots=N_SLOTS, state_prefix="@sm/",
            admit_buckets=buckets,
            sampling=SamplingConfig(temperature=1.0, top_k=8),
            **kwargs)
    with unique_name.guard():
        spec_k0 = T.build_decode_step_program(
            n_slots=N_SLOTS, state_prefix="@s0/",
            admit_buckets=buckets,
            draft=DraftConfig(d_model=DD, n_heads=H, n_layers=L,
                              d_inner=32, k=0), **kwargs)
    return {"exe": exe, "scope": scope, "inc_m": inc_m,
            "inc_buf": inc_buf, "spec": spec,
            "pspec": pspec, "sampled": sampled, "spec_k0": spec_k0,
            "draft": draft, "kwargs": kwargs}


def _oracle(tr, srcs):
    ref, = tr["exe"].run(tr["inc_m"], feed={"src_ids": srcs},
                         fetch_list=[tr["inc_buf"]],
                         scope=tr["scope"])
    return apply_eos_sentinel(np.asarray(ref), end_id=END_ID)


def _serve(tr, bundle, srcs, order=None, seeds=None, cls=None,
           **srv_kw):
    cls = cls or (PagedContinuousGenerationServer
                  if bundle.cache.layout == "paged"
                  else ContinuousGenerationServer)
    n = len(srcs)
    order = list(order) if order is not None else list(range(n))
    with cls(bundle, executor=tr["exe"], scope=tr["scope"],
             **srv_kw) as srv:
        replies = {}
        for i in order:
            kw = {"seed": seeds[i]} if seeds is not None else {}
            replies[i] = srv.submit(srcs[i], **kw)
        got = np.stack([replies[i].result(timeout=300.0)
                        for i in range(n)])
        st = srv.stats()
    return got, st


class TestGreedySpecParity:
    def test_token_exact_with_slot_reuse(self, trained):
        """12 mixed-length requests through 4 slots (3x reuse): every
        speculative row equals the whole-loop greedy row, sentinel
        tails included — AND the trained draft actually accepts (the
        speedup premise, not just correctness)."""
        srcs = _mixed_len_prompts(np.random.RandomState(11), 12)
        want = _oracle(trained, srcs)
        assert len(set((w != -1).sum() for w in want)) > 1
        got, st = _serve(trained, trained["spec"], srcs)
        np.testing.assert_array_equal(got, want)
        sp = st["speculative"]
        assert sp["k"] == K
        # both tiny models learned the same copy task: the draft must
        # agree with the target well above chance
        assert sp["acceptance_rate"] is not None \
            and sp["acceptance_rate"] > 0.3, sp

    def test_independent_of_admission_order(self, trained):
        srcs = _mixed_len_prompts(np.random.RandomState(13), 8)
        want = _oracle(trained, srcs)
        got, _ = _serve(trained, trained["spec"], srcs,
                        order=range(7, -1, -1))
        np.testing.assert_array_equal(got, want)

    def test_burst_length_does_not_move_tokens(self, trained):
        srcs = _mixed_len_prompts(np.random.RandomState(17), 6)
        want = _oracle(trained, srcs)
        got1, _ = _serve(trained, trained["spec"], srcs,
                         steps_per_tick=1, drain_steps=1)
        np.testing.assert_array_equal(got1, want)
        got6, st = _serve(trained, trained["spec"], srcs,
                          steps_per_tick=6)
        np.testing.assert_array_equal(got6, want)
        # the multi-token lever is real: fewer target model steps
        # than tokens emitted
        sp = st["speculative"]
        assert sp["target_steps"] < sp["emitted"]

    def test_token_exact_paged(self, trained):
        """The PAGED spec server: multi-position verify writes go
        through lane-exclusive block-table scatter; prefix hit/miss
        admission tiers both carry the draft state."""
        rng = np.random.RandomState(19)
        srcs = _mixed_len_prompts(rng, 10)
        srcs[5] = srcs[0]  # a prefix HIT mid-stream
        want = _oracle(trained, srcs)
        got, st = _serve(trained, trained["pspec"], srcs)
        np.testing.assert_array_equal(got, want)
        assert st["block_pool"]["prefix_hits"] >= 1

    def test_k0_degenerates_to_plain_path(self, trained):
        """DraftConfig(k=0) = the r10 one-token step: token parity
        with the whole-loop oracle (= the plain bundle's own pinned
        contract, tests/test_continuous_batching.py) and no
        speculative machinery in the stats."""
        srcs = _mixed_len_prompts(np.random.RandomState(23), 6)
        want = _oracle(trained, srcs)
        got_k0, st = _serve(trained, trained["spec_k0"], srcs)
        np.testing.assert_array_equal(got_k0, want)
        assert "speculative" not in st  # no draft machinery ran


class TestSpecCounters:
    def test_counter_units(self, trained):
        """emitted == generated tokens (the buffer-content count),
        draft_steps == k * target_steps (k draft model steps per
        verify), accepted <= proposed, and the emitted stream is
        accepted proposals + one correction/bonus per lane-tick."""
        srcs = _mixed_len_prompts(np.random.RandomState(29), 8)
        got, st = _serve(trained, trained["spec"], srcs)
        sp = st["speculative"]
        assert sp["draft_steps"] == K * sp["target_steps"]
        assert 0 <= sp["accepted"] <= sp["proposed"]
        assert sp["emitted"] == int(
            count_generated_tokens(got, END_ID).sum())
        assert sp["accepted"] <= sp["emitted"]
        # per LANE-tick units: a lane advances 1..k+1 tokens per
        # verify (regression: an emitted/program-ticks version scaled
        # with occupancy and reported 21.7 at 8 live lanes)
        assert 1.0 <= sp["mean_accepted_len"] <= K + 1
        assert st["tokens"] == sp["emitted"]

    def test_metrics_and_span_surface(self, trained):
        """The uniquely-labeled pull-provider samples exist with the
        device-counter values."""
        srcs = _mixed_len_prompts(np.random.RandomState(31), 4)
        with ContinuousGenerationServer(
                trained["spec"], executor=trained["exe"],
                scope=trained["scope"]) as srv:
            for s in srcs:
                srv.submit(s).result(timeout=300.0)
            samples = {name: val for name, lab, val
                       in srv._metrics_samples()}
            sp = srv.stats()["speculative"]
        for key in ("proposed", "accepted", "emitted", "draft_steps",
                    "target_steps"):
            assert samples[f"paddle_tpu_spec_{key}_total"] == sp[key]
        assert "paddle_tpu_spec_acceptance_rate" in samples


class TestSampledLanes:
    def test_bit_identical_across_admission_orders(self, trained):
        """Fixed per-request seeds: the sampled stream of every
        request is byte-identical whatever order admitted it (noise
        is keyed on (seed, position), never on lane/tick/dispatch)."""
        srcs = _mixed_len_prompts(np.random.RandomState(37), 8)
        seeds = list(range(100, 108))
        a, _ = _serve(trained, trained["sampled"], srcs, seeds=seeds)
        b, _ = _serve(trained, trained["sampled"], srcs, seeds=seeds,
                      order=range(7, -1, -1))
        np.testing.assert_array_equal(a, b)
        # content-derived default seeds: resubmission reproduces too
        c1, _ = _serve(trained, trained["sampled"], srcs)
        c2, _ = _serve(trained, trained["sampled"], srcs,
                       order=range(7, -1, -1))
        np.testing.assert_array_equal(c1, c2)

    def test_seeds_vary_the_stream(self, trained):
        """Distinct seeds on ONE prompt: the noise channel is alive
        (>= 2 distinct generations across 16 seeds), and the same
        seed twice is identical. Uses the no-EOS pool prompt: its
        full-buffer generation gives the tail probabilities ~31
        positions to fire on."""
        src = PROMPT_POOL[-1]
        outs = []
        with ContinuousGenerationServer(
                trained["sampled"], executor=trained["exe"],
                scope=trained["scope"]) as srv:
            for seed in range(16):
                outs.append(tuple(
                    srv.submit(src, seed=seed).result(300.0)))
            again = tuple(srv.submit(src, seed=3).result(300.0))
        assert len(set(outs)) >= 2
        assert again == outs[3]

    def test_distribution_centers_on_greedy_mode(self, trained):
        """Sampled-lane sanity on the trained terminator-copy task:
        across many seeds the MODAL first generated token is the
        greedy (argmax) token — the filtered sampler draws from the
        model's distribution, not some shifted one."""
        src = _mixed_len_prompts(np.random.RandomState(43), 1)
        greedy_first = _oracle(trained, src)[0, 1]
        firsts = []
        with ContinuousGenerationServer(
                trained["sampled"], executor=trained["exe"],
                scope=trained["scope"]) as srv:
            for seed in range(30):
                toks = srv.submit(src[0], seed=seed).result(300.0)
                firsts.append(int(toks[1]))
        vals, counts = np.unique(firsts, return_counts=True)
        assert vals[np.argmax(counts)] == greedy_first, (
            list(zip(vals.tolist(), counts.tolist())), greedy_first)


class TestExecutableBound:
    def test_zero_steady_state_compiles_under_churn(self, trained):
        """100 mixed-length requests through the speculative server
        compile NOTHING after its fused serve set binds."""
        exe = trained["exe"]
        srv = ContinuousGenerationServer(
            trained["spec"], executor=exe, scope=trained["scope"])
        try:
            assert srv._warmed_compiles <= len(
                trained["spec"].serves)
            warmed = exe.compile_count
            srcs = _mixed_len_prompts(np.random.RandomState(47), 100)
            replies = [srv.submit(s) for s in srcs]
            got = [r.result(timeout=600.0) for r in replies]
            st = srv.stats()
        finally:
            srv.close()
        assert len(got) == 100
        assert exe.compile_count == warmed, (
            f"steady-state spec traffic compiled "
            f"{exe.compile_count - warmed} executable(s)")
        assert st["completed"] == 100


class TestFingerprints:
    def test_spec_and_sampled_bundles_never_dedupe(self, trained):
        """server_fingerprint separates plain / spec / spec-k0 /
        sampled bundles over the SAME weights — the runtime must
        never hot-swap or dedupe them as one model."""
        from types import SimpleNamespace

        from paddle_tpu.inference.runtime.registry import \
            server_fingerprint

        fps = {name: server_fingerprint(
                   SimpleNamespace(bundle=trained[name]))
               for name in ("spec", "pspec", "sampled", "spec_k0")}
        assert len(set(fps.values())) == len(fps), fps

    def test_colliding_draft_prefix_refused_at_build(self, trained):
        """The ModelRegistry-style PTA100 pair lint at bundle build:
        a draft whose params would alias the target's raises."""
        from paddle_tpu.models import transformer as T

        with pytest.raises(ValueError, match="PTA100"):
            T.build_decode_step_program(
                n_slots=2, state_prefix="@bad/",
                draft=DraftConfig(d_model=D, n_heads=H, n_layers=L,
                                  d_inner=64, k=1, prefix=""),
                **trained["kwargs"])
