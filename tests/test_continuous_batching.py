"""Continuous batching for generation serving
(inference/serving.py ContinuousGenerationServer +
models/transformer.py build_decode_step_program).

Covers the two invariants the slot-pool design must hold:

* token-exact greedy parity with the whole-loop incremental decode —
  same prompts give identical sentinel-normalized token rows, for
  mixed output lengths (EOS mid-stream via the terminator-copy task),
  through slot reuse, independent of admission order, and on the
  K-step-scan tick path;
* zero steady-state compiles — executable count is fixed at the
  fused serve set (one program per admission bucket) no matter how
  many mixed-length requests churn through the pool;

plus the continuous >= static throughput regression guard and the
serving-observability surface (slot occupancy, TTFT, per-token
latency, retired/s).
"""
import time

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.inference import (ContinuousGenerationServer,
                                  GenerationServer, apply_eos_sentinel,
                                  count_generated_tokens)

V, D, L, S, MAXT = 16, 64, 1, 12, 64
END_ID = 1


def _mixed_len_prompts(rng, n):
    """Terminator-copy prompts: random tokens with end_id planted at a
    random position — the trained copy model then emits EOS there, so
    served generations have MIXED lengths (the workload continuous
    batching exists for)."""
    src = rng.randint(3, V, (n, S)).astype(np.int64)
    for r in range(n):
        p = rng.randint(1, S + 1)
        if p < S:
            src[r, p:] = END_ID
    return src


def _zipf_prompts(rng, n):
    """Zipf-ish workload: most prompts plant EOS in the first few
    positions (short generations), a fat tail has NO terminator and
    decodes to the full buffer — the mixed-length mix where
    head-of-line blocking hurts the whole-loop server most."""
    src = rng.randint(3, V, (n, S)).astype(np.int64)
    for r in range(n):
        p = int(rng.choice([1, 2, 3, S], p=[0.4, 0.25, 0.15, 0.2]))
        if p < S:
            src[r, p:] = END_ID
    return src


@pytest.fixture(scope="module")
def trained():
    """Train the tiny terminator-copy transformer once; build the
    whole-loop incremental decode (the parity oracle / static leg)
    and the slot-pool bundle against the same scope-shared weights."""
    from paddle_tpu import unique_name
    from paddle_tpu.core.scope import Scope
    from paddle_tpu.models import transformer as T

    # module-private scope: the autouse _fresh_state fixture resets
    # the GLOBAL scope per test, which would wipe the trained weights
    scope = Scope()
    with unique_name.guard():
        main, startup, loss = T.build_program(
            seq_len=S, d_model=D, n_heads=2, n_layers=L, d_inner=128,
            vocab=V, with_optimizer=False, dropout_rate=0.0)
        with fluid.program_guard(main, startup):
            fluid.optimizer.Adam(learning_rate=0.005).minimize(loss)
    exe = fluid.Executor(fluid.TPUPlace(0))
    exe.run(startup, scope=scope)
    rng = np.random.RandomState(7)
    for _ in range(400):
        src = _zipf_prompts(rng, 8)
        tgt_in = np.concatenate(
            [np.full((8, 1), 2, np.int64), src[:, :-1]], 1)
        exe.run(main, feed={"src_ids": src, "tgt_ids": tgt_in,
                            "label": src}, fetch_list=[loss],
                scope=scope)
    kwargs = dict(seq_len=S, max_out_len=MAXT, d_model=D, n_heads=2,
                  n_layers=L, d_inner=128, vocab=V, start_id=2,
                  end_id=END_ID)
    with unique_name.guard():
        inc_m, _, _, inc_buf = T.build_incremental_decode_program(
            **kwargs)
    with unique_name.guard():
        bundle = T.build_decode_step_program(n_slots=8, **kwargs)
    return {"exe": exe, "scope": scope, "inc_m": inc_m,
            "inc_buf": inc_buf, "bundle": bundle, "rng": rng}


def _oracle(tr, srcs):
    """Whole-loop incremental decode of the same prompts, sentinel-
    normalized (batch-composition-independent form)."""
    ref, = tr["exe"].run(tr["inc_m"], feed={"src_ids": srcs},
                         fetch_list=[tr["inc_buf"]],
                         scope=tr["scope"])
    return apply_eos_sentinel(np.asarray(ref), end_id=END_ID)


class TestParity:
    def test_token_exact_vs_whole_loop_with_slot_reuse(self, trained):
        """24 mixed-length requests through 8 slots (3x reuse): every
        row must equal the whole-loop decode row, -1 sentinel tails
        included."""
        srcs = _mixed_len_prompts(np.random.RandomState(11), 24)
        want = _oracle(trained, srcs)
        assert len(set((w != -1).sum() for w in want)) > 1, \
            "workload must have mixed output lengths"
        with ContinuousGenerationServer(
                trained["bundle"], executor=trained["exe"],
                scope=trained["scope"]) as srv:
            replies = [srv.submit(s) for s in srcs]
            got = np.stack([r.result(timeout=120.0) for r in replies])
            st = srv.stats()
        np.testing.assert_array_equal(got, want)
        assert st["completed"] == 24
        assert st["requests"] == 24

    def test_independent_of_admission_order(self, trained):
        """Reversed submission order: each prompt still decodes to
        exactly its own row (lanes cannot interact — row-wise ops
        only)."""
        srcs = _mixed_len_prompts(np.random.RandomState(13), 10)
        want = _oracle(trained, srcs)
        with ContinuousGenerationServer(
                trained["bundle"], executor=trained["exe"],
                scope=trained["scope"]) as srv:
            order = list(range(10))[::-1]
            replies = {i: srv.submit(srcs[i]) for i in order}
            got = np.stack([replies[i].result(timeout=120.0)
                            for i in range(10)])
        np.testing.assert_array_equal(got, want)

    def test_burst_length_does_not_move_tokens(self, trained):
        """steps_per_tick=1 vs the default burst: the fused serve
        While runs a different number of device ticks per dispatch —
        tokens must not move."""
        srcs = _mixed_len_prompts(np.random.RandomState(17), 8)
        want = _oracle(trained, srcs)
        with ContinuousGenerationServer(
                trained["bundle"], executor=trained["exe"],
                scope=trained["scope"], steps_per_tick=1,
                drain_steps=1) as srv:
            replies = [srv.submit(s) for s in srcs]
            got = np.stack([r.result(timeout=120.0) for r in replies])
        np.testing.assert_array_equal(got, want)
        with ContinuousGenerationServer(
                trained["bundle"], executor=trained["exe"],
                scope=trained["scope"], steps_per_tick=6) as srv:
            replies = [srv.submit(s) for s in srcs]
            got2 = np.stack([r.result(timeout=120.0)
                             for r in replies])
            st = srv.stats()
        np.testing.assert_array_equal(got2, want)
        # the burst amortization actually happened: fewer dispatches
        # than tokens emitted
        assert st["ticks"] < st["tokens"]
        # exit-on-retire scheduling (the min_active feed) moves
        # dispatch boundaries, never tokens
        with ContinuousGenerationServer(
                trained["bundle"], executor=trained["exe"],
                scope=trained["scope"], exit_on_retire=True) as srv:
            replies = [srv.submit(s) for s in srcs]
            got3 = np.stack([r.result(timeout=120.0)
                             for r in replies])
        np.testing.assert_array_equal(got3, want)

    def test_standalone_step_program_scan_parity(self, trained):
        """The bundle's standalone single-step program composes with
        Executor.prepare(steps=K) (the run_steps inner lax.scan): K
        scanned ticks equal K sequential ticks, token-for-token."""
        bundle, exe = trained["bundle"], trained["exe"]
        scope = trained["scope"]
        srcs = _mixed_len_prompts(np.random.RandomState(37), 2)
        sn = bundle.state
        fetches = [sn["tok_buf"], sn["step"], sn["finished"]]

        def admit_and_run(tick):
            bundle.init_slot_state(scope)
            pre = exe.prepare(
                bundle.prefills[2],
                feed=[("src_ids", (2, S), "int64"),
                      ("slots", (2,), "int64")],
                fetch_list=[], scope=scope)
            pre.run({"src_ids": srcs,
                     "slots": np.array([0, 1], np.int64)})
            return tick()

        seq = exe.prepare(bundle.step, feed={}, fetch_list=fetches,
                          scope=scope)
        toks_seq = admit_and_run(
            lambda: [seq.run({}) for _ in range(6)][-1][0])
        scanned = exe.prepare(bundle.step, feed={},
                              fetch_list=fetches, scope=scope,
                              steps=3)
        assert scanned.fallback_reason is None  # the scan path bound
        toks_scan = admit_and_run(
            lambda: [scanned.run({}) for _ in range(2)][-1][0][-1])
        np.testing.assert_array_equal(np.asarray(toks_scan)[:2],
                                      np.asarray(toks_seq)[:2])


class TestExecutableBound:
    def test_zero_steady_state_compiles_under_churn(self, trained):
        """100 mixed-length requests churning through 8 slots compile
        NOTHING after the fused serve set (one executable per
        admission bucket) binds: the slot-pool design admits any
        request mix through fixed shapes."""
        exe = trained["exe"]
        srv = ContinuousGenerationServer(
            trained["bundle"], executor=exe, scope=trained["scope"])
        try:
            # one executable per serve bucket {0,1,2,4,8}
            assert srv._warmed_compiles <= len(
                trained["bundle"].serves)
            warmed = exe.compile_count
            srcs = _mixed_len_prompts(np.random.RandomState(19), 100)
            replies = [srv.submit(s) for s in srcs]
            got = [r.result(timeout=300.0) for r in replies]
            st = srv.stats()
        finally:
            srv.close()
        assert len(got) == 100
        assert exe.compile_count == warmed, (
            f"steady-state traffic compiled "
            f"{exe.compile_count - warmed} fresh executable(s)")
        assert st["completed"] == 100
        # every retirement freed a slot for the next arrival: the pool
        # stayed busy (>= half occupied on average under a full queue)
        assert st["slot_occupancy"] and st["slot_occupancy"] >= 0.5


class TestCustomAdmitLadder:
    def test_ladder_smaller_than_slots_caps_admissions(self, trained):
        """A bundle whose admission-bucket ladder covers less than
        n_slots must not kill the scheduler when more slots than the
        largest bucket are free — overflow admissions wait one
        cycle (regression: _bucket_for raised out of the scheduler
        thread and every future hung)."""
        from paddle_tpu import unique_name
        from paddle_tpu.models import transformer as T

        with unique_name.guard():
            bundle = T.build_decode_step_program(
                seq_len=S, max_out_len=MAXT, d_model=D, n_heads=2,
                n_layers=L, d_inner=128, vocab=V, start_id=2,
                end_id=END_ID, n_slots=4, admit_buckets=[1, 2],
                state_prefix="@cb2/")
        srcs = _mixed_len_prompts(np.random.RandomState(41), 5)
        want = _oracle(trained, srcs)
        with ContinuousGenerationServer(
                bundle, executor=trained["exe"],
                scope=trained["scope"]) as srv:
            replies = [srv.submit(s) for s in srcs]
            got = np.stack([r.result(timeout=120.0) for r in replies])
        np.testing.assert_array_equal(got, want)


class TestObservability:
    def test_stats_surface(self, trained):
        srcs = _mixed_len_prompts(np.random.RandomState(23), 8)
        with ContinuousGenerationServer(
                trained["bundle"], executor=trained["exe"],
                scope=trained["scope"]) as srv:
            replies = [srv.submit(s) for s in srcs]
            got = np.stack([r.result(timeout=120.0) for r in replies])
            st = srv.stats()
        assert st["slots"] == 8
        assert 0 < st["slot_occupancy"] <= 1.0
        assert st["ttft_ms"]["p50"] is not None
        assert st["ttft_ms"]["p99"] >= st["ttft_ms"]["p50"]
        # TTFT strictly precedes completion for multi-token requests
        assert st["ttft_ms"]["p50"] <= st["latency_ms"]["p50"]
        assert st["per_token_ms"]["p50"] is not None
        assert st["retired_per_s"] and st["retired_per_s"] > 0
        assert st["tokens"] == int(
            count_generated_tokens(got, END_ID).sum())

    def test_whole_loop_server_reports_slots_and_ttft(self, trained):
        """The satellite observability on the STATIC server: TTFT,
        per-token latency, slot occupancy (its padded batch rows)."""
        srv = GenerationServer(
            trained["inc_m"], trained["inc_buf"],
            executor=trained["exe"], scope=trained["scope"],
            end_id=END_ID, max_batch_size=4, max_wait_ms=5.0)
        try:
            srcs = _mixed_len_prompts(np.random.RandomState(29), 6)
            replies = [srv.submit({"src_ids": s[None]}) for s in srcs]
            for r in replies:
                r.result(timeout=120.0)
            st = srv.stats()
        finally:
            srv.close()
        assert st["slots"] == 4  # its padded batch rows
        assert st["slot_occupancy"] == st["batch_occupancy"]
        assert st["ttft_ms"]["p50"] is not None
        assert st["per_token_ms"]["p50"] is not None
        assert st["tokens"] > 0
        assert st["retired_per_s"] and st["retired_per_s"] > 0


class TestThroughputGuard:
    def test_continuous_not_slower_than_static(self, trained):
        """Regression guard (CPU analogue of the PERF.md continuous-
        batching table): on a mixed-length workload the slot-pool
        server must sustain at least the whole-loop GenerationServer's
        tokens/s. The measured win is ~1.5-3x (BENCH_SELF_r10.json).

        Floor widened from a MEASURED contention floor (the PR 13
        contention-flake leftover): the legs here are ~50-70 ms —
        dispatch-dominated — and under FULL-lane contention on this
        throttled 2-core host the continuous server's scheduler
        thread competes for cores, with a measured best paired
        speedup of 0.87x in a full fast-lane run that passed alone
        at >= 1x. 0.80 still catches a real regression (the
        pre-fusion slot pool measured 0.7x, PERF.md) while clearing
        the contention band; the 1.5-3x claim itself is bench.py's
        to defend, not this smoke guard's."""
        exe, scope = trained["exe"], trained["scope"]
        srcs = _zipf_prompts(np.random.RandomState(31), 64)
        want = _oracle(trained, srcs)
        total_tokens = int(count_generated_tokens(want, END_ID).sum())

        def static_leg():
            srv = GenerationServer(
                trained["inc_m"], trained["inc_buf"], executor=exe,
                scope=scope, end_id=END_ID, max_batch_size=8,
                max_wait_ms=2.0)
            try:
                t0 = time.perf_counter()
                replies = [srv.submit({"src_ids": s[None]})
                           for s in srcs]
                for r in replies:
                    r.result(timeout=300.0)
                return time.perf_counter() - t0
            finally:
                srv.close()

        def continuous_leg():
            srv = ContinuousGenerationServer(
                trained["bundle"], executor=exe, scope=scope,
                steps_per_tick=8)
            try:
                t0 = time.perf_counter()
                replies = [srv.submit(s) for s in srcs]
                for r in replies:
                    r.result(timeout=300.0)
                return time.perf_counter() - t0
            finally:
                srv.close()

        # warm both paths, then 3 INTERLEAVED (static, continuous)
        # pairs and the best PAIRED ratio: this host's CPU-throttle
        # windows last seconds, so comparing each leg's global best
        # can pit one server's lucky window against the other's
        # throttled one and report a 2x-off ratio (PERF.md
        # "Continuous batching" measurement note). Adjacent legs
        # share a window; three pairs make it vanishingly unlikely
        # every pair straddles a throttle transition.
        pairs = [(static_leg(), continuous_leg()) for _ in range(3)]
        best = max(s / c for s, c in pairs)
        assert best >= 0.80, (
            f"continuous batching regressed: best paired speedup "
            f"{best:.2f}x over the static server on the mixed-length "
            f"workload (pairs: "
            f"{[(round(s, 3), round(c, 3)) for s, c in pairs]}; "
            f"{total_tokens} tokens)")
