"""Warm-start layer (core/compile_cache.py + Executor prepared
dispatch): content-addressed disk cache round trips (zero in-process
compiles in a warmed process — proven cross-process by subprocess),
invalidation on program mutation and toolchain version change,
corrupt-entry tolerance (named reason, never a crash), the StableHLO
persistence fallback, the in-memory LRU executable-cache bound, and
PreparedProgram parity/staleness guards."""
import json
import os
import pickle
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.core import compile_cache as cc
from paddle_tpu.core.executor import ExecutableCache
from paddle_tpu.flags import FLAGS, set_flags

FEED = {"x": np.ones((2, 4), np.float32)}


def _fresh():
    fluid._reset_global_scope()
    from paddle_tpu import unique_name

    unique_name.switch()
    fluid.seed(90)


def _build():
    prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(prog, startup):
        x = fluid.layers.data(name="x", shape=[4], dtype="float32")
        h = fluid.layers.fc(x, size=8, act="relu")
        out = fluid.layers.fc(h, size=3)
        loss = fluid.layers.mean(out)
        fluid.optimizer.SGD(0.1).minimize(loss)
    return prog, startup, loss


def _enable(tmp_path, mode="rw"):
    set_flags({"FLAGS_compile_cache": mode,
               "FLAGS_compile_cache_dir": str(tmp_path / "cc")})


def _train_pass(steps=None):
    """One identical build+train pass: fresh scope/names/seed, run
    startup, one train step (or a K-step scan). Returns (result,
    executor, program)."""
    _fresh()
    prog, startup, loss = _build()
    exe = fluid.Executor(fluid.TPUPlace())
    exe.run(startup)
    if steps is None:
        out = exe.run(prog, feed=FEED, fetch_list=[loss])
    else:
        out = exe.run_steps(prog, feed=FEED, fetch_list=[loss],
                            steps=steps)
    return np.asarray(out[0]), exe, prog


class TestFingerprint:
    def test_identical_builds_agree_uid_does_not_matter(self):
        _fresh()
        p1, _, _ = _build()
        _fresh()
        p2, _, _ = _build()
        assert p1._uid != p2._uid
        assert p1.fingerprint() == p2.fingerprint()

    def test_mutation_changes_fingerprint(self):
        _fresh()
        prog, _, loss = _build()
        fp = prog.fingerprint()
        prog.global_block.append_op(
            "scale", {"X": [loss.name]}, {"Out": [loss.name]},
            {"scale": 2.0})
        assert prog.fingerprint() != fp

    def test_clone_preserves_fingerprint(self):
        # clone() keeps structure + op uids -> same executable content
        _fresh()
        prog, _, _ = _build()
        assert prog.clone().fingerprint() == prog.fingerprint()


class TestDiskRoundTrip:
    def test_block_round_trip_zero_compiles(self, tmp_path):
        _enable(tmp_path)
        r1, exe1, _ = _train_pass()
        assert exe1.compile_count > 0 and exe1.disk_load_count == 0
        r2, exe2, _ = _train_pass()
        assert exe2.compile_count == 0, \
            f"warmed pass compiled {exe2.compile_count}x"
        assert exe2.disk_load_count > 0
        np.testing.assert_array_equal(r1, r2)  # bit-exact rehydration

    def test_scan_round_trip_zero_compiles(self, tmp_path):
        _enable(tmp_path)
        r1, exe1, _ = _train_pass(steps=3)
        assert exe1.last_run_steps_fallback is None
        r2, exe2, _ = _train_pass(steps=3)
        assert exe2.compile_count == 0
        assert exe2.disk_load_count > 0
        np.testing.assert_array_equal(r1, r2)

    def test_ro_mode_never_writes(self, tmp_path):
        _enable(tmp_path, mode="ro")
        _, exe, _ = _train_pass()
        assert exe.compile_count > 0
        root = tmp_path / "cc"
        files = [f for _, _, fs in os.walk(root) for f in fs] \
            if root.exists() else []
        assert files == [], f"ro cache wrote {files}"

    def test_version_bump_is_a_miss_not_a_stale_hit(self, tmp_path):
        _enable(tmp_path)
        _train_pass()
        _fresh()
        prog, startup, loss = _build()
        prog.global_block.append_op(
            "scale", {"X": [loss.name]}, {"Out": [loss.name]},
            {"scale": 10.0})
        exe = fluid.Executor(fluid.TPUPlace())
        exe.run(startup)
        out = exe.run(prog, feed=FEED, fetch_list=[loss])
        # the mutated program must compile fresh (startup itself may
        # disk-hit; the train step may not)
        assert exe.compile_count >= 1
        base, exe_b, _ = _train_pass()
        np.testing.assert_allclose(np.asarray(out[0]), base * 10.0,
                                   rtol=1e-5)

    def test_spoofed_toolchain_version_is_a_miss(self, tmp_path,
                                                 monkeypatch):
        _enable(tmp_path)
        _train_pass()
        real = cc.version_token()
        monkeypatch.setattr(
            cc, "version_token",
            lambda: dict(real, jaxlib="99.99.99-spoofed"))
        _, exe, _ = _train_pass()
        assert exe.disk_load_count == 0  # no cross-version hit
        assert exe.compile_count > 0

    def test_framework_source_change_is_a_miss(self, tmp_path,
                                               monkeypatch):
        """The program fingerprint hashes op DESCS, not KERNELS — an
        ops/ numerics fix must invalidate persisted executables via
        the source token, never serve the old math."""
        _enable(tmp_path)
        _train_pass()
        monkeypatch.setattr(cc, "_SOURCE_TOKEN",
                            ["simulated-kernel-edit"])
        _, exe, _ = _train_pass()
        assert exe.disk_load_count == 0
        assert exe.compile_count > 0

    def test_corrupt_entry_recompiles_with_named_reason(self,
                                                        tmp_path):
        _enable(tmp_path)
        r1, _, _ = _train_pass()
        n_truncated = 0
        for dirpath, _, files in os.walk(tmp_path / "cc"):
            for f in files:
                p = os.path.join(dirpath, f)
                with open(p, "r+b") as fh:
                    fh.truncate(8)
                n_truncated += 1
        assert n_truncated > 0
        cc._CACHES.clear()  # fresh counters for the assertion
        with pytest.warns(UserWarning, match="discarding entry"):
            r2, exe, _ = _train_pass()
        assert exe.compile_count > 0  # recompiled, did not crash
        cache = cc.active_cache()
        assert cache.discards, "no named discard reason recorded"
        assert any("corrupt" in reason or "format" in reason
                   for _, reason in cache.discards)
        np.testing.assert_array_equal(r1, r2)

    def test_host_effect_programs_never_enter_the_disk_cache(
            self, tmp_path):
        """io_callback closures are process-local pointers: a
        persisted executable carrying one would crash a fresh
        process. Host-bridging programs must stay process-local —
        nothing stored, nothing loaded."""
        _enable(tmp_path)
        _fresh()
        prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(prog, startup):
            x = fluid.layers.data("x", shape=[4], dtype="float32")
            y = fluid.layers.scale(x, scale=2.0)
            sink = prog.current_block().create_var(
                name="he_sink", shape=[-1, 4], dtype="float32")
            fluid.layers.py_func(lambda a: np.asarray(a), y,
                                 out=sink)
            loss = fluid.layers.mean(y)
        exe = fluid.Executor(fluid.TPUPlace())
        sc = fluid.Scope()
        exe.run(startup, scope=sc)
        exe.run(prog, feed=FEED, fetch_list=[loss], scope=sc)
        # startup (pure) may persist; the py_func program must not —
        # a fresh identical build must recompile it, never disk-load
        _fresh()
        prog2, startup2 = fluid.Program(), fluid.Program()
        with fluid.program_guard(prog2, startup2):
            x = fluid.layers.data("x", shape=[4], dtype="float32")
            y = fluid.layers.scale(x, scale=2.0)
            sink = prog2.current_block().create_var(
                name="he_sink", shape=[-1, 4], dtype="float32")
            fluid.layers.py_func(lambda a: np.asarray(a), y,
                                 out=sink)
            loss2 = fluid.layers.mean(y)
        exe2 = fluid.Executor(fluid.TPUPlace())
        sc2 = fluid.Scope()
        exe2.run(startup2, scope=sc2)
        disk_before = exe2.disk_load_count
        out = exe2.run(prog2, feed=FEED, fetch_list=[loss2],
                       scope=sc2)
        assert exe2.disk_load_count == disk_before  # no host-op load
        assert exe2.compile_count >= 1
        np.testing.assert_allclose(np.asarray(out[0]).reshape(-1),
                                   [2.0], rtol=1e-6)

    def test_stablehlo_fallback_round_trip(self, tmp_path):
        """serialize_executable unavailable -> entries persist lowered
        StableHLO; loads skip tracing and redo only the backend
        compile."""
        cc._FORCE_STABLEHLO[0] = True
        try:
            _enable(tmp_path)
            r1, exe1, _ = _train_pass()
            assert exe1.compile_count > 0
            entries = []
            for dirpath, _, files in os.walk(tmp_path / "cc"):
                for f in files:
                    with open(os.path.join(dirpath, f), "rb") as fh:
                        entries.append(pickle.load(fh))
            assert entries and all(
                e["format"] == "stablehlo" for e in entries)
            r2, exe2, _ = _train_pass()
            assert exe2.compile_count == 0
            assert exe2.disk_load_count > 0
            np.testing.assert_allclose(r1, r2, rtol=1e-6)
        finally:
            cc._FORCE_STABLEHLO[0] = False


class TestDiskGC:
    """FLAGS_compile_cache_max_entries/_max_bytes: LRU-by-mtime
    pruning on write — the bound multi-model swap churn needs (the
    runtime loads/retires fingerprints; without GC the cache dir
    grows forever)."""

    def _serve_shapes(self, prog, startup, loss, shapes):
        exe = fluid.Executor(fluid.TPUPlace())
        exe.run(startup)
        for b in shapes:
            exe.run(prog, feed={"x": np.ones((b, 4), np.float32)},
                    fetch_list=[loss])
        return exe

    def test_prune_on_write_bounds_entries_and_counts(self, tmp_path):
        import time as _time

        _enable(tmp_path)
        _fresh()
        prog, startup, loss = _build()
        # startup + one block entry per feed shape land on disk
        self._serve_shapes(prog, startup, loss, (1, 2, 4))
        cache = cc.active_cache()
        n0 = cache.disk_usage()["entries"]
        assert n0 >= 4  # startup + 3 shapes
        # age every existing entry so mtime ordering is unambiguous
        # (sub-second writes can tie)
        now = _time.time()
        for i, (path, _m, _s) in enumerate(sorted(cache._entries())):
            os.utime(path, (now - 1000 + i, now - 1000 + i))
        set_flags({"FLAGS_compile_cache_max_entries": n0 - 1})
        self._serve_shapes(prog, startup, loss, (8,))  # + 1 store
        assert cache.disk_usage()["entries"] == n0 - 1
        assert cache.prune_count >= 1
        assert cache.stats()["prunes"] == cache.prune_count

    def test_byte_bound_prunes_oldest_first(self, tmp_path):
        import time as _time

        _enable(tmp_path)
        _fresh()
        prog, startup, loss = _build()
        self._serve_shapes(prog, startup, loss, (1, 2))
        cache = cc.active_cache()
        usage = cache.disk_usage()
        now = _time.time()
        for i, (path, _m, _s) in enumerate(sorted(cache._entries())):
            os.utime(path, (now - 1000 + i, now - 1000 + i))
        # bound at the CURRENT total: the next write overflows it and
        # must shed the oldest entries until back under
        set_flags({"FLAGS_compile_cache_max_bytes":
                   int(usage["bytes"])})
        self._serve_shapes(prog, startup, loss, (4,))
        assert cache.disk_usage()["bytes"] <= usage["bytes"]
        assert cache.prune_count >= 1

    def test_load_refreshes_mtime_so_hot_entries_survive(
            self, tmp_path):
        """An entry a process warm-started from recently must NOT be
        the one GC sheds: load refreshes mtime (LRU, not FIFO)."""
        import time as _time

        _enable(tmp_path)
        _fresh()
        prog, startup, loss = _build()
        self._serve_shapes(prog, startup, loss, (2,))
        cache = cc.active_cache()
        before = {p for p, _m, _s in cache._entries()}
        self._serve_shapes(prog, startup, loss, (4,))
        (path_b,) = [p for p, _m, _s in cache._entries()
                     if p not in before]        # the shape-4 entry
        now = _time.time()
        for p, _m, _s in cache._entries():
            # everything old; the shape-4 entry the YOUNGEST cold one
            os.utime(p, (now - 1000, now - 1000))
        os.utime(path_b, (now - 500, now - 500))
        # disk-load shape 2 in a FRESH executor (private in-memory
        # cache -> forced to the disk path): refreshes the mtimes of
        # everything it rehydrates (startup + shape-2), leaving
        # path_b the LRU entry
        exe2 = self._serve_shapes(prog, startup, loss, (2,))
        assert exe2.compile_count == 0 and exe2.disk_load_count > 0
        n = cache.disk_usage()["entries"]
        set_flags({"FLAGS_compile_cache_max_entries": n})
        self._serve_shapes(prog, startup, loss, (8,))  # overflow by 1
        assert not os.path.exists(path_b), \
            "the cold entry should have been pruned first (LRU)"
        assert cache.disk_usage()["entries"] == n

    def test_prune_sweeps_stale_tmp_debris(self, tmp_path):
        """A writer killed between mkstemp and os.replace leaves a
        .tmp the entry walk never counts; _prune must sweep stale
        ones (crash debris) but leave recent ones (live writers)."""
        import time as _time

        _enable(tmp_path)
        _fresh()
        prog, startup, loss = _build()
        self._serve_shapes(prog, startup, loss, (1,))
        cache = cc.active_cache()
        sub = os.path.dirname(cache._entries()[0][0])
        stale = os.path.join(sub, "dead-writer-a.tmp")
        fresh = os.path.join(sub, "live-writer-b.tmp")
        for p in (stale, fresh):
            with open(p, "wb") as f:
                f.write(b"x" * 128)
        now = _time.time()
        os.utime(stale, (now - 3600, now - 3600))
        set_flags({"FLAGS_compile_cache_max_entries": 64})
        self._serve_shapes(prog, startup, loss, (2,))  # triggers prune
        assert not os.path.exists(stale), "crash debris must be swept"
        assert os.path.exists(fresh), \
            "a recent .tmp may be a live concurrent writer"


class TestExecutableCacheLRU:
    def test_capacity_bound_and_eviction_counter(self):
        _fresh()
        prog, startup, loss = _build()
        exe = fluid.Executor(fluid.TPUPlace(),
                             cache=ExecutableCache(capacity=2))
        exe.run(startup)
        for b in (1, 2, 3):  # three feed-shape specializations
            exe.run(prog, feed={"x": np.ones((b, 4), np.float32)},
                    fetch_list=[loss])
        assert len(exe._cache) <= 2
        assert exe.cache_evict_count >= 1

    def test_version_bump_stranded_entries_get_evicted(self):
        """Pass.apply-style mutations strand the old executable under
        an unreachable key; the LRU cap reclaims it instead of
        leaking one executable per mutation forever."""
        _fresh()
        prog, startup, loss = _build()
        exe = fluid.Executor(fluid.TPUPlace(),
                             cache=ExecutableCache(capacity=2))
        exe.run(startup)
        for i in range(4):
            exe.run(prog, feed=FEED, fetch_list=[loss])
            prog.global_block.append_op(
                "scale", {"X": [loss.name]}, {"Out": [loss.name]},
                {"scale": 1.0})  # bump _version, strand the entry
        assert len(exe._cache) <= 2
        assert exe.cache_evict_count >= 2

    def test_default_capacity_comes_from_flag(self):
        assert ExecutableCache().capacity == \
            FLAGS.executor_cache_capacity

    def test_lru_recency_order(self):
        c = ExecutableCache(capacity=2)
        c["a"], c["b"] = 1, 2
        assert c.get("a") == 1  # refresh a
        c["c"] = 3              # evicts b, not a
        assert "a" in c and "b" not in c and "c" in c
        assert c.evict_count == 1


class TestPreparedProgram:
    def test_parity_with_run(self):
        r1, _, _ = _train_pass()
        _fresh()
        prog, startup, loss = _build()
        exe = fluid.Executor(fluid.TPUPlace())
        exe.run(startup)
        prep = exe.prepare(prog, FEED, fetch_list=[loss])
        out = prep.run(FEED)
        np.testing.assert_array_equal(np.asarray(out[0]), r1)

    def test_prepare_from_specs(self):
        _fresh()
        prog, startup, loss = _build()
        exe = fluid.Executor(fluid.TPUPlace())
        exe.run(startup)
        prep = exe.prepare(prog, [("x", (2, 4), "float32")],
                           fetch_list=[loss])
        out = prep.run(FEED)
        assert np.isfinite(np.asarray(out[0])).all()

    def test_rebind_on_program_mutation(self):
        """A Pass.apply-style version bump between prepared calls must
        re-resolve, never serve the stale executable."""
        _fresh()
        prog, startup, loss = _build()
        exe = fluid.Executor(fluid.TPUPlace())
        exe.run(startup)
        prep = exe.prepare(prog, FEED, fetch_list=[loss])
        prep.run(FEED)
        compiles = exe.compile_count
        prog.global_block.append_op(
            "scale", {"X": [loss.name]}, {"Out": [loss.name]},
            {"scale": 10.0})
        out2 = prep.run(FEED)
        assert exe.compile_count > compiles  # re-resolved
        # the x10 rewrite is visible through the prepared handle
        np.testing.assert_allclose(np.asarray(out2[0]) / 10.0,
                                   _replay_second_step(), rtol=1e-5)

    def test_feed_spec_mismatch_is_a_named_error(self):
        _fresh()
        prog, startup, loss = _build()
        exe = fluid.Executor(fluid.TPUPlace())
        exe.run(startup)
        prep = exe.prepare(prog, FEED, fetch_list=[loss])
        with pytest.raises(ValueError, match="bound for feed"):
            prep.run({"x": np.ones((5, 4), np.float32)})
        with pytest.raises(ValueError, match="missing"):
            prep.run({})
        # same count, wrong NAME: named error, not a raw KeyError
        with pytest.raises(ValueError, match="unknown=\\['y'\\]"):
            prep.run({"y": np.ones((2, 4), np.float32)})

    def test_prepared_scan_parity_with_run_steps(self):
        r1, _, _ = _train_pass(steps=3)
        _fresh()
        prog, startup, loss = _build()
        exe = fluid.Executor(fluid.TPUPlace())
        exe.run(startup)
        prep = exe.prepare(prog, FEED, fetch_list=[loss], steps=3)
        assert prep.fallback_reason is None
        out = prep.run(FEED)
        np.testing.assert_array_equal(np.asarray(out[0]), r1)

    def test_prepared_scan_fallback_named_reason(self):
        """Host-bridging ops cannot scan; the prepared handle keeps
        the run_steps contract (stacked fetches + named reason)."""
        _fresh()
        prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(prog, startup):
            x = fluid.layers.data("x", shape=[4], dtype="float32")
            y = fluid.layers.scale(x, scale=2.0)
            sink = prog.current_block().create_var(
                name="pp_sink", shape=[-1, 4], dtype="float32")
            fluid.layers.py_func(lambda a: np.asarray(a), y, out=sink)
            loss = fluid.layers.mean(y)
        exe = fluid.Executor(fluid.TPUPlace())
        sc = fluid.Scope()
        exe.run(startup, scope=sc)
        prep = exe.prepare(prog, FEED, fetch_list=[loss], steps=3,
                           scope=sc)
        assert prep.fallback_reason is not None
        assert "host" in prep.fallback_reason
        out = prep.run(FEED)
        np.testing.assert_allclose(np.asarray(out[0]).reshape(-1),
                                   [2.0] * 3, rtol=1e-6)


def _replay_second_step():
    """Two sequential train steps on a fresh identical build; returns
    the second step's loss (what a mutated x-10 fetch is compared
    against in test_rebind_on_program_mutation)."""
    _fresh()
    prog, startup, loss = _build()
    exe = fluid.Executor(fluid.TPUPlace())
    exe.run(startup)
    exe.run(prog, feed=FEED, fetch_list=[loss])
    out = exe.run(prog, feed=FEED, fetch_list=[loss])
    return np.asarray(out[0])


_SUBPROCESS_SCRIPT = r"""
import json
import numpy as np
import paddle_tpu as fluid
from paddle_tpu.inference.serving import InferenceServer, ProgramRunner

fluid.seed(7)
prog, startup = fluid.Program(), fluid.Program()
with fluid.program_guard(prog, startup):
    x = fluid.layers.data(name="x", shape=[6], dtype="float32")
    h = fluid.layers.fc(x, size=8, act="relu")
    out = fluid.layers.fc(h, size=3)
exe = fluid.Executor(fluid.TPUPlace())
exe.run(startup)
runner = ProgramRunner(prog, ["x"], [out.name], executor=exe,
                       scope=fluid.global_scope())
with InferenceServer(runner, max_batch_size=4, max_wait_ms=1.0) as srv:
    srv.aot_warmup()
    res = srv.infer({"x": np.ones((1, 6), np.float32)})
    st = srv.stats()
print(json.dumps({"compile_count": st["compile_count"],
                  "disk_load_count": st["disk_load_count"],
                  "out": np.asarray(res[0]).tolist()}))
"""


class TestSubprocessRoundTrip:
    def test_disk_warmed_fresh_process_serves_with_zero_compiles(
            self, tmp_path):
        """The acceptance proof: process A populates the cache;
        process B — a genuinely fresh python process — AOT-warms the
        whole bucket ladder and serves with compile_count == 0."""
        env = dict(os.environ,
                   JAX_PLATFORMS="cpu",
                   FLAGS_compile_cache="rw",
                   FLAGS_compile_cache_dir=str(tmp_path / "cc"))

        def run_once(tag):
            proc = subprocess.run(
                [sys.executable, "-c", _SUBPROCESS_SCRIPT],
                capture_output=True, text=True, env=env, timeout=300)
            assert proc.returncode == 0, \
                f"{tag} failed:\n{proc.stderr[-2000:]}"
            return json.loads(proc.stdout.strip().splitlines()[-1])

        a = run_once("process A (cold)")
        assert a["compile_count"] > 0
        b = run_once("process B (disk-warmed)")
        assert b["compile_count"] == 0, \
            f"warmed process compiled: {b}"
        assert b["disk_load_count"] > 0
        # identical serving results across the process boundary
        np.testing.assert_allclose(a["out"], b["out"], rtol=1e-6)
