"""Divergence & sharding prover tests (paddle_tpu/analysis/absint).

The crafted positive fixtures re-build the two REAL incidents the
prover exists for (CLAUDE.md round-5 learnings):

* the 1F1B x tp trap — a vocab-sharded logits psum landing inside a
  per-STAGE lax.cond branch, so devices at different pp coordinates
  disagree on the collective order and deadlock (PTA130 at ERROR,
  with the divergence source named in the proof);
* the replicated-input-grad trap — differentiating a REPLICATED
  input inside a divergent branch, whose transpose psum lands inside
  the branch (PTA131 at ERROR; applying the r5 `_vary` fix silences
  it).
"""
import warnings

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.analysis import (ERROR, INFO, WARNING, absint,
                                 check_bundle, run_checks)


def _diags(program, code):
    return [d for d in run_checks(program) if d.code == code]


def _guarded():
    main, startup = fluid.Program(), fluid.Program()
    return main, startup, fluid.program_guard(main, startup)


# ---------------------------------------------------------------------------
# engine basics: lattice, seed table, marking
# ---------------------------------------------------------------------------
class TestEngine:
    def test_join_order(self):
        assert absint.join(absint.REPLICATED, absint.VARYING) \
            == absint.VARYING
        assert absint.join(absint.VARYING, absint.UNKNOWN) \
            == absint.UNKNOWN
        assert absint.join(absint.REPLICATED, absint.REPLICATED) \
            == absint.REPLICATED

    def test_mark_requires_registered_tag(self):
        main, startup, g = _guarded()
        with g:
            x = layers.fill_constant([1], "float32", 0.0)
            with pytest.raises(ValueError, match="unknown divergence"):
                absint.mark_divergence_source(x, "not_a_tag")

    def test_register_refuses_silent_redefinition(self):
        absint.register_divergence_source("_t_tag", "a test tag")
        absint.register_divergence_source("_t_tag", "a test tag")
        with pytest.raises(ValueError, match="different description"):
            absint.register_divergence_source("_t_tag", "changed")

    def test_marked_value_propagates_varying(self):
        main, startup, g = _guarded()
        with g:
            stage = layers.fill_constant([1], "float32", 0.0)
            absint.mark_divergence_source(stage, "pp_stage_id")
            derived = layers.scale(stage, 2.0)
            plain = layers.fill_constant([1], "float32", 1.0)
        facts = absint.analyze(main)
        assert facts.value(stage.name).repl == absint.VARYING
        assert facts.value(stage.name).source == "pp_stage_id"
        assert facts.value(derived.name).repl == absint.VARYING
        assert facts.value(plain.name).repl == absint.REPLICATED

    def test_while_guard_classified_and_fixpoint_converges(self):
        # the serve-cond pattern: cond minted from a varying mask,
        # refreshed INSIDE the body — needs the fixpoint to classify
        main, startup, g = _guarded()
        with g:
            mask = layers.fill_constant([4], "int64", 1)
            absint.mark_divergence_source(mask, "lane_active_mask")
            live = layers.reduce_sum(mask, keep_dim=True)
            limit = layers.fill_constant([1], "int64", 0.0)
            cond = layers.greater_than(live, limit)
            w = layers.While(cond)
            with w.block():
                layers.greater_than(
                    layers.reduce_sum(mask, keep_dim=True), limit,
                    cond=cond)
        facts = absint.analyze(main)
        assert facts.converged
        guarded = list(facts.guarded_sites())
        assert guarded, "while body sites must carry the guard"
        for _site, guards in guarded:
            assert guards[0].container_type == "while"
            assert guards[0].fact == absint.VARYING
            assert guards[0].source == "lane_active_mask"

    def test_shipped_serve_while_is_proven_divergent(self):
        # decode_engine annotates _serve_cond with lane_active_mask:
        # the whole burst body must sit under a PROVEN-divergent guard
        from paddle_tpu.models import transformer as T

        bundle = T.build_decode_step_program(
            seq_len=4, max_out_len=6, d_model=16, n_heads=2,
            n_layers=1, d_inner=32, vocab=16, n_slots=2,
            state_prefix="@absint_sv/")
        facts = absint.analyze(bundle.serves[0])
        guarded = list(facts.guarded_sites())
        assert guarded
        assert all(facts.divergent(g) for _s, g in guarded)


# ---------------------------------------------------------------------------
# PTA130: the r5 1F1B x tp vocab-psum-in-branch fixture
# ---------------------------------------------------------------------------
def _vocab_psum_in_stage_branch():
    """Crafted 1F1B x tp shape: a per-STAGE predicate (marked
    pp_stage_id) gating a branch whose body computes vocab logits and
    psums them over the tp axis — the exact r5 deadlock, as a
    Program."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[8], dtype="float32")
        stage = layers.fill_constant([1], "float32", 0.0)
        absint.mark_divergence_source(stage, "pp_stage_id")
        pred = layers.less_than_value(stage, 1.0)
        sub = main.create_block()
        # vocab-sharded logits partial matmul + the tp psum: modeled
        # by an op carrying the shard_map axis_name attr (what the
        # sharded lowering emits)
        sub.append_op("scale", {"X": [x.name]}, {"Out": ["logits_p"]},
                      {"scale": 1.0})
        sub.append_op("sync_batch_norm", {"X": ["logits_p"]},
                      {"Y": ["logits"]}, {"axis_name": "tp"})
        main.rollback()
        fsub = main.create_block()
        fsub.append_op("scale", {"X": [x.name]}, {"Out": ["logits_f"]},
                       {"scale": 1.0})
        main.rollback()
        main.global_block.append_op(
            "conditional_block",
            {"Condition": [pred.name], "X": [x.name]},
            {"Out": ["b_out"]},
            {"true_block": sub, "false_block": fsub,
             "true_out": "logits", "false_out": "logits_f"})
    return main


class TestPTA130:
    def test_vocab_psum_in_stage_branch_is_proven_error(self):
        main = _vocab_psum_in_stage_branch()
        ds = _diags(main, "PTA130")
        assert ds and ds[0].severity == ERROR
        assert "PROVEN" in ds[0].message
        assert "pp_stage_id" in ds[0].message

    def test_unmarked_cond_still_errors_like_pta010(self):
        # agreement with the pattern matcher's stance: a collective
        # under ANY traced guard is an error even when the predicate
        # is value-uniform. Since the twin dedupe, the prover OWNS
        # the covered site — PTA010 defers (fires only when the
        # fixpoint engine is unavailable)
        main, startup, g = _guarded()
        with g:
            from paddle_tpu.layers.collective import _allreduce

            x = layers.data("x", shape=[4], dtype="float32")
            pred = layers.less_than_value(
                layers.fill_constant([1], "float32", 0.0), 1.0)
            layers.cond(pred,
                        lambda: _allreduce(layers.scale(x, 2.0)),
                        lambda: layers.scale(x, 1.0))
        p130 = _diags(main, "PTA130")
        p010 = _diags(main, "PTA010")
        assert p130 and p130[0].severity == ERROR
        assert "value-uniform" in p130[0].message
        assert p010 == []  # the dedupe: one incident, one diagnostic

    def test_scope_collective_upgraded_under_divergent_guard(self):
        # PTA011 warns on attention-in-while; under a PROVEN-divergent
        # guard the scoped lowering WILL deadlock -> PTA130 ERROR
        main, startup, g = _guarded()
        with g:
            mask = layers.fill_constant([1], "int64", 1)
            absint.mark_divergence_source(mask, "lane_active_mask")
            limit = layers.fill_constant([1], "int64", 0.0)
            cond = layers.greater_than(mask, limit)
            w = layers.While(cond)
            with w.block():
                blk = main.current_block()
                blk.append_op("attention", {"Q": ["q"]},
                              {"Out": ["o"]}, {})
                layers.greater_than(mask, limit, cond=cond)
        ds = _diags(main, "PTA130")
        assert ds and ds[0].severity == ERROR
        assert "PROVEN divergent" in ds[0].message
        # the twin dedupe: the covered site is the prover's alone
        assert _diags(main, "PTA011") == []

    def test_top_level_collective_is_clean(self):
        main, startup, g = _guarded()
        with g:
            from paddle_tpu.layers.collective import _allreduce

            x = layers.data("x", shape=[4], dtype="float32")
            _allreduce(layers.scale(x, 2.0))
        assert not _diags(main, "PTA130")


# ---------------------------------------------------------------------------
# PTA131: replicated-input grad / sharded value in divergent context
# ---------------------------------------------------------------------------
def _grad_in_stage_branch(vary_fix=False):
    """Crafted replicated-input-grad-in-cond: a backward-role op
    inside a stage-gated branch producing w@GRAD for a replicated
    parameter. With vary_fix=True the input is cast varying BEFORE
    the branch (the r5 `_vary` fix) and the prover must go silent."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        w = main.global_block.create_parameter(
            name="stage_w", shape=[4, 4], dtype="float32")
        stage = layers.fill_constant([1], "float32", 0.0)
        absint.mark_divergence_source(stage, "pp_stage_id")
        pred = layers.less_than_value(stage, 1.0)
        src = w
        if vary_fix:
            src = layers.scale(w, 1.0)
            absint.mark_divergence_source(src, "vary")
        sub = main.create_block()
        sub.append_op("scale_grad", {"X": [src.name],
                                     "Out@GRAD": ["g_in"]},
                      {"X@GRAD": [src.name + "@GRAD"]},
                      {"op_role": "backward"})
        main.rollback()
        fsub = main.create_block()
        fsub.append_op("scale", {"X": [src.name]}, {"Out": ["noop"]},
                       {"scale": 1.0})
        main.rollback()
        main.global_block.append_op(
            "conditional_block",
            {"Condition": [pred.name], "X": [src.name]},
            {"Out": ["out"]},
            {"true_block": sub, "false_block": fsub,
             "true_out": src.name + "@GRAD", "false_out": "noop"})
    return main


class TestPTA131:
    def test_replicated_grad_in_divergent_branch_is_error(self):
        ds = _diags(_grad_in_stage_branch(), "PTA131")
        assert ds and ds[0].severity == ERROR
        assert "psum INSIDE the branch" in ds[0].message
        assert ds[0].var == "stage_w"

    def test_vary_fix_silences_it(self):
        # the r5 discipline: cast varying BEFORE the branch
        assert not _diags(_grad_in_stage_branch(vary_fix=True),
                          "PTA131")

    def test_uniform_guard_is_silent(self):
        # differentiating under a value-uniform predicate is fine:
        # every mesh program instance takes the same path
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            w = main.global_block.create_parameter(
                name="u_w", shape=[4, 4], dtype="float32")
            pred = layers.less_than_value(
                layers.fill_constant([1], "float32", 0.0), 1.0)
            sub = main.create_block()
            sub.append_op("scale_grad", {"X": [w.name],
                                         "Out@GRAD": ["g_in"]},
                          {"X@GRAD": ["u_w@GRAD"]},
                          {"op_role": "backward"})
            main.rollback()
            fsub = main.create_block()
            fsub.append_op("scale", {"X": [w.name]},
                           {"Out": ["noop"]}, {"scale": 1.0})
            main.rollback()
            main.global_block.append_op(
                "conditional_block",
                {"Condition": [pred.name], "X": [w.name]},
                {"Out": ["out"]},
                {"true_block": sub, "false_block": fsub,
                 "true_out": "u_w@GRAD", "false_out": "noop"})
        assert not _diags(main, "PTA131")

    def test_sharded_value_in_divergent_branch_is_error(self):
        main, startup, g = _guarded()
        with g:
            x = layers.data("x", shape=[8], dtype="float32")
            h = layers.scale(x, 1.0)
            absint.mark_sharded(h, ("model",))
            mask = layers.fill_constant([1], "int64", 1)
            absint.mark_divergence_source(mask, "lane_active_mask")
            limit = layers.fill_constant([1], "int64", 0.0)
            cond = layers.greater_than(mask, limit)
            w = layers.While(cond)
            with w.block():
                layers.scale(h, 2.0)
                layers.greater_than(mask, limit, cond=cond)
        ds = _diags(main, "PTA131")
        assert ds and ds[0].severity == ERROR
        assert "sharding annotation" in ds[0].message
        assert ds[0].var == h.name

    def test_sharded_value_outside_branches_is_clean(self):
        main, startup, g = _guarded()
        with g:
            x = layers.data("x", shape=[8], dtype="float32")
            h = layers.scale(x, 1.0)
            absint.mark_sharded(h, ("model",))
            layers.scale(h, 2.0)
        assert not _diags(main, "PTA131")


# ---------------------------------------------------------------------------
# PTA140: declared shape/dtype clobbered by producer inference (r10)
# ---------------------------------------------------------------------------
class TestPTA140:
    def test_r10_concrete_persistable_clobbered_is_error(self):
        # THE incident: assign of a [-1,4] value onto a concretely-
        # declared persistable rewrites the declaration
        main, startup, g = _guarded()
        with g:
            sink = main.global_block.create_var(
                name="@decl_sink", shape=(8, 4), dtype="float32",
                persistable=True, stop_gradient=True)
            x = layers.data("x", shape=[4], dtype="float32")
            layers.assign(layers.scale(x, 2.0), output=sink)
            layers.scale(sink, 1.0)  # read it: not PTA090's class
        assert tuple(sink.shape) != (8, 4)  # inference DID clobber
        ds = _diags(main, "PTA140")
        assert ds and ds[0].severity == ERROR
        assert ds[0].var == "@decl_sink"
        assert "(8, 4)" in ds[0].message

    def test_static_batch_producer_is_clean(self):
        main, startup, g = _guarded()
        with g:
            sink = main.global_block.create_var(
                name="@decl_ok", shape=(8, 4), dtype="float32",
                persistable=True, stop_gradient=True)
            x = layers.data("x", shape=[8, 4], dtype="float32",
                            append_batch_size=False)
            layers.assign(layers.scale(x, 2.0), output=sink)
            layers.scale(sink, 1.0)
        assert not _diags(main, "PTA140")

    def test_int_persistable_promoted_to_float_warns(self):
        # the PTA020 class generalized beyond `increment`: any
        # producer that promotes a declared-int contract var
        main, startup, g = _guarded()
        with g:
            ctr = main.global_block.create_var(
                name="@int_ctr", shape=(1,), dtype="int64",
                persistable=True, stop_gradient=True)
            f = layers.fill_constant([1], "float32", 1.5)
            main.global_block.append_op(
                "elementwise_add", {"X": [ctr.name], "Y": [f.name]},
                {"Out": [ctr.name]}, {})
            layers.scale(ctr, 1.0)
        ds = _diags(main, "PTA140")
        assert ds and any("promoted" in d.message for d in ds)
        assert all(d.severity in (WARNING, ERROR) for d in ds)

    def test_float_temp_promotion_is_exempt(self):
        # int temp scaled by a float step is ordinary arithmetic —
        # only contract vars (persistable/data/carried) are findings
        main, startup, g = _guarded()
        with g:
            i = layers.fill_constant([1], "int64", 3)
            layers.mean(layers.scale(i, 0.5))
        assert not [d for d in _diags(main, "PTA140")
                    if "promoted" in d.message]

    def test_zoo_style_programs_are_clean(self):
        from paddle_tpu.models import mnist

        main, startup, *_ = mnist.build_program(use_conv=False)
        assert not _diags(main, "PTA140")
        assert not _diags(startup, "PTA140")


# ---------------------------------------------------------------------------
# PTA150: whole-bundle contracts
# ---------------------------------------------------------------------------
@pytest.fixture(scope="module")
def small_bundle():
    from paddle_tpu.models import transformer as T

    return T.build_decode_step_program(
        seq_len=4, max_out_len=6, d_model=16, n_heads=2, n_layers=1,
        d_inner=32, vocab=16, n_slots=2, state_prefix="@b150/")


class TestPTA150Bundle:
    def test_shipped_bundle_is_clean(self, small_bundle):
        assert check_bundle(small_bundle) == []

    def test_geometry_disagreement_is_error(self, small_bundle):
        serve = small_bundle.serves[0]
        name = small_bundle.state["tok_buf"]
        var = serve.global_block.vars[name]
        old = var.shape
        try:
            var.shape = (old[0], old[1] + 1)
            var._declared_shape = var.shape
            ds = check_bundle(small_bundle)
            assert ds and ds[0].code == "PTA150" \
                and ds[0].severity == ERROR
            assert "geometry" in ds[0].message
        finally:
            var.shape = old
            del var._declared_shape

    def test_missing_counter_is_error(self, small_bundle):
        serve = small_bundle.serves[0]
        name = small_bundle.state["step"]
        var = serve.global_block.vars.pop(name)
        try:
            ds = check_bundle(small_bundle)
            assert ds and any(
                d.severity == ERROR and d.var == name and
                "stale" in d.message for d in ds)
        finally:
            serve.global_block.vars[name] = var

    def test_seed_derivation_drift_is_error(self):
        from paddle_tpu.models import transformer as T
        from paddle_tpu.models.decode_engine import SamplingConfig

        bundle = T.build_decode_step_program(
            seq_len=4, max_out_len=6, d_model=16, n_heads=2,
            n_layers=1, d_inner=32, vocab=16, n_slots=2,
            state_prefix="@b150s/", admit_buckets=[2],
            sampling=SamplingConfig(temperature=0.8, base_seed=7))
        assert check_bundle(bundle) == []
        # drift ONE specialization's base_seed: the same logical draw
        # would no longer replay byte-identically across programs
        from paddle_tpu.analysis import iter_ops

        drifted = None
        for site in iter_ops(bundle.serves[2]):
            if "base_seed" in site.op.attrs:
                drifted = site.op
                break
        assert drifted is not None
        old = drifted.attrs["base_seed"]
        try:
            drifted.attrs["base_seed"] = old + 1
            ds = check_bundle(bundle)
            assert ds and all(d.code == "PTA150" for d in ds)
            assert any("base_seed" in d.message and
                       d.severity == ERROR for d in ds)
        finally:
            drifted.attrs["base_seed"] = old


# ---------------------------------------------------------------------------
# suppression contract (_pta_suppress)
# ---------------------------------------------------------------------------
class TestSuppression:
    def _collective_prog(self):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            from paddle_tpu.layers.collective import _allreduce

            x = layers.data("x", shape=[4], dtype="float32")
            pred = layers.less_than_value(
                layers.fill_constant([1], "float32", 0.0), 1.0)
            layers.cond(pred,
                        lambda: _allreduce(layers.scale(x, 2.0)),
                        lambda: layers.scale(x, 1.0))
        return main

    def test_suppression_drops_and_collects(self):
        main = self._collective_prog()
        inner = [op for blk in main.blocks for op in blk.ops
                 if op.type == "allreduce"]
        assert inner
        inner[0].attrs["_pta_suppress"] = (
            "PTA130", "single-host test program, never meshed")
        collected = []
        ds = run_checks(main, collect_suppressed=collected)
        assert "PTA130" not in {d.code for d in ds}
        assert collected and collected[0][0].code == "PTA130"
        assert "never meshed" in collected[0][1]

    def test_executor_strict_gate_honors_suppression(self):
        main = self._collective_prog()
        inner = [op for blk in main.blocks for op in blk.ops
                 if op.type == "allreduce"]
        assert inner
        inner[0].attrs["_pta_suppress"] = (
            "PTA130", "crafted: documents the trap")
        assert not [d for d in run_checks(main)
                    if d.severity == ERROR]

    def test_malformed_suppression_warns_and_ignores(self):
        main = self._collective_prog()
        inner = [op for blk in main.blocks for op in blk.ops
                 if op.type == "allreduce"]
        inner[0].attrs["_pta_suppress"] = "PTA130"  # not a pair
        ds = run_checks(main)
        assert "PTA199" in {d.code for d in ds}
        assert "PTA130" in {d.code for d in ds}  # NOT suppressed

    def test_suppression_only_matches_its_anchor(self):
        main = self._collective_prog()
        # suppress at an unrelated op: the finding must survive
        main.global_block.ops[0].attrs["_pta_suppress"] = (
            "PTA130", "wrong anchor")
        assert "PTA130" in {d.code for d in run_checks(main)}


# ---------------------------------------------------------------------------
# dataflow entry-name registry (the PTA001 over-seeding fix)
# ---------------------------------------------------------------------------
class TestBlockEntryRegistry:
    def test_output_name_lists_no_longer_seed(self):
        # a while op whose sub-block reads a name that ONLY appears in
        # a non-entry list attr: the old any-all-str-list heuristic
        # seeded it and masked the uninit read
        main, startup, g = _guarded()
        with g:
            sub = main.create_block()
            sub.append_op("scale", {"X": ["ghost"]}, {"Out": ["s"]},
                          {"scale": 1.0})
            main.rollback()
            main.global_block.append_op(
                "while", {"Condition": ["c"], "X": [], "Init": []},
                {"Out": []},
                {"sub_block": sub, "carried": [], "externals": [],
                 "bogus_names": ["ghost"]})
        ds = _diags(main, "PTA001")
        assert any(d.var == "ghost" for d in ds)

    def test_registered_entry_attrs_still_seed(self):
        main, startup, g = _guarded()
        with g:
            x = layers.data("x", shape=[4], dtype="float32")
            sub = main.create_block()
            sub.append_op("scale", {"X": ["carried_v"]},
                          {"Out": ["carried_v"]}, {"scale": 1.0})
            main.rollback()
            main.global_block.append_op(
                "while", {"Condition": ["c"], "X": [x.name],
                          "Init": [x.name]},
                {"Out": ["carried_v"]},
                {"sub_block": sub, "carried": ["carried_v"],
                 "externals": []})
        assert not [d for d in _diags(main, "PTA001")
                    if d.var == "carried_v"]

    def test_unknown_container_falls_back_with_warning(self):
        from paddle_tpu.analysis.dataflow import (
            _ENTRY_FALLBACK_WARNED, block_entry_names)
        from paddle_tpu.core.program import Operator

        op = Operator(None, "_t_custom_container", {"X": ["a"]}, {},
                      {"some_names": ["seeded"]})
        _ENTRY_FALLBACK_WARNED.discard("_t_custom_container")
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            names = block_entry_names(op)
        assert "seeded" in names  # permissive fallback
        assert any("register_block_entry_attrs" in str(w.message)
                   for w in caught)
        # warn-once: second call is silent
        with warnings.catch_warnings(record=True) as caught2:
            warnings.simplefilter("always")
            block_entry_names(op)
        assert not caught2

    def test_registration_makes_it_exact(self):
        from paddle_tpu.analysis.dataflow import (
            BLOCK_ENTRY_ATTRS, block_entry_names,
            register_block_entry_attrs)
        from paddle_tpu.core.program import Operator

        register_block_entry_attrs("_t_reg_container", ("ins",))
        try:
            op = Operator(None, "_t_reg_container", {}, {},
                          {"ins": ["a"], "outs": ["b"]})
            names = block_entry_names(op)
            assert "a" in names and "b" not in names
        finally:
            del BLOCK_ENTRY_ATTRS["_t_reg_container"]


# ---------------------------------------------------------------------------
# baseline payload/diff machinery (no zoo build: crafted reports)
# ---------------------------------------------------------------------------
class TestBaseline:
    def _report(self, target, diags, suppressed=()):
        from paddle_tpu.analysis.baseline import TargetReport

        rep = TargetReport(target)
        rep.diagnostics = list(diags)
        rep.suppressed = list(suppressed)
        return rep

    def _diag(self, code, severity, var=None, op_type=None):
        from paddle_tpu.analysis import Diagnostic

        return Diagnostic(code, severity, "msg", var=var,
                          op_type=op_type)

    def test_payload_records_gated_and_suppressed(self):
        from paddle_tpu.analysis.baseline import baseline_payload

        reps = [self._report(
            "models/x:main",
            [self._diag("PTA130", ERROR, var="v"),
             self._diag("PTA011", WARNING),
             self._diag("PTA003", INFO)],
            suppressed=[(self._diag("PTA010", ERROR), "why")])]
        pay = baseline_payload(reps)
        assert pay["entries"] == {
            "models/x:main|PTA130|error||v": 1,
            "models/x:main|PTA011|warning||": 1}
        assert pay["suppressed"] == {
            "models/x:main|PTA010|error||": 1}
        assert pay["totals"]["infos"] == 1

    def test_diff_flags_new_and_reports_resolved(self):
        from paddle_tpu.analysis.baseline import (baseline_payload,
                                                  diff_against_baseline)

        base = baseline_payload([self._report(
            "t:main", [self._diag("PTA011", WARNING)])])
        now = [self._report(
            "t:main", [self._diag("PTA011", WARNING),
                       self._diag("PTA140", WARNING, var="s")])]
        new, resolved = diff_against_baseline(now, base)
        assert new == ["t:main|PTA140|warning||s (x1 new)"]
        assert resolved == []
        fixed = [self._report("t:main", [])]
        new2, resolved2 = diff_against_baseline(fixed, base)
        assert new2 == []
        assert resolved2 == ["t:main|PTA011|warning|| (-1)"]

    def test_new_suppression_fails_until_baselined(self):
        # a fresh _pta_suppress drops the diagnostic from --strict,
        # so the drift gate must catch it through the suppressed
        # section — and stop failing once the baseline records it
        from paddle_tpu.analysis.baseline import (baseline_payload,
                                                  diff_against_baseline)

        base = baseline_payload([self._report("t:main", [])])
        now = [self._report(
            "t:main", [],
            suppressed=[(self._diag("PTA010", ERROR), "wip")])]
        new, _res = diff_against_baseline(now, base)
        assert new == ["t:main|PTA010|error|| (x1 new [suppressed])"]
        refreshed = baseline_payload(now)
        assert diff_against_baseline(now, refreshed) == ([], [])

    def test_write_load_roundtrip(self, tmp_path):
        from paddle_tpu.analysis.baseline import (
            diff_against_baseline, load_baseline, write_baseline)

        reps = [self._report("t:main",
                             [self._diag("PTA011", WARNING)])]
        path = str(tmp_path / "base.json")
        write_baseline(reps, path)
        base = load_baseline(path)
        assert diff_against_baseline(reps, base) == ([], [])

    def test_cli_baseline_rejects_partial_sweeps(self):
        # the drift gate is only meaningful over the FULL zoo: a
        # shrunk sweep hides new findings as vacuous 'resolved'
        from paddle_tpu.analysis.__main__ import main

        assert main(["--baseline", "x.json", "--only", "mnist"]) == 2
        assert main(["--baseline", "x.json", "--no-benchmark"]) == 2
        assert main(["--write-baseline", "x.json",
                     "--no-benchmark"]) == 2


# ---------------------------------------------------------------------------
# registry declaration recording (the PTA140 evidence base)
# ---------------------------------------------------------------------------
class TestDeclarationRecording:
    def test_first_clobber_stashes_declaration(self):
        main, startup, g = _guarded()
        with g:
            v = main.global_block.create_var(
                name="decl_v", shape=(8, 4), dtype="float32",
                persistable=True)
            x = layers.data("x", shape=[4], dtype="float32")
            layers.assign(layers.scale(x, 2.0), output=v)
        assert v._declared_shape == (8, 4)
        assert tuple(v.shape) == (-1, 4)

    def test_inferred_shapes_are_not_declarations(self):
        # a shapeless temp written twice with different inferred
        # shapes must NOT record a declaration (PTA002-legal temps)
        main, startup, g = _guarded()
        with g:
            x4 = layers.data("x4", shape=[4], dtype="float32")
            x8 = layers.data("x8", shape=[8], dtype="float32")
            blk = main.global_block
            blk.append_op("scale", {"X": [x4.name]}, {"Out": ["t"]},
                          {"scale": 1.0})
            blk.append_op("scale", {"X": [x8.name]}, {"Out": ["t"]},
                          {"scale": 1.0})
        t = main.global_block.vars["t"]
        assert not hasattr(t, "_declared_shape")

    def test_matching_inference_keeps_declaration_armed(self):
        # declared (8,4), first producer agrees, second clobbers:
        # the stash must still capture the DECLARED (8,4)
        main, startup, g = _guarded()
        with g:
            v = main.global_block.create_var(
                name="armed_v", shape=(8, 4), dtype="float32",
                persistable=True)
            ok = layers.data("ok", shape=[8, 4], dtype="float32",
                             append_batch_size=False)
            bad = layers.data("bad", shape=[4], dtype="float32")
            layers.assign(layers.scale(ok, 1.0), output=v)
            assert not hasattr(v, "_declared_shape")
            layers.assign(layers.scale(bad, 1.0), output=v)
        assert v._declared_shape == (8, 4)
