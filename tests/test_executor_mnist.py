"""End-to-end training tests: the minimum slice (BASELINE config 1) plus
executor behaviours (reference tests/book/test_recognize_digits.py +
test_executor_* patterns)."""
import numpy as np
import pytest

import paddle_tpu as fluid


def _mnist_fc_program():
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        img = fluid.layers.data("img", shape=[784], dtype="float32")
        label = fluid.layers.data("label", shape=[1], dtype="int64")
        hidden = fluid.layers.fc(img, 64, act="relu")
        logits = fluid.layers.fc(hidden, 10)
        loss = fluid.layers.softmax_with_cross_entropy(logits, label)
        avg_loss = fluid.layers.mean(loss)
    return main, startup, avg_loss


def _synthetic_batch(rng, n=64):
    """Linearly separable 'digits': class pattern + noise."""
    y = rng.randint(0, 10, (n, 1)).astype("int64")
    x = rng.rand(n, 784).astype("float32") * 0.3
    for i in range(n):
        c = int(y[i, 0])
        x[i, c * 78:(c + 1) * 78] += 1.0
    return x, y


def test_mnist_fc_sgd_converges():
    main, startup, avg_loss = _mnist_fc_program()
    with fluid.program_guard(main, startup):
        fluid.optimizer.SGD(learning_rate=0.5).minimize(avg_loss)
    exe = fluid.Executor(fluid.TPUPlace())
    exe.run(startup)
    rng = np.random.RandomState(7)
    losses = []
    for _ in range(60):
        x, y = _synthetic_batch(rng)
        (out,) = exe.run(main, feed={"img": x, "label": y},
                         fetch_list=[avg_loss])
        losses.append(float(np.asarray(out).reshape(-1)[0]))
    assert losses[-1] < losses[0] * 0.8, losses[:5] + losses[-5:]


def test_mnist_fc_adam_converges():
    main, startup, avg_loss = _mnist_fc_program()
    with fluid.program_guard(main, startup):
        fluid.optimizer.Adam(learning_rate=0.01).minimize(avg_loss)
    exe = fluid.Executor(fluid.TPUPlace())
    exe.run(startup)
    rng = np.random.RandomState(7)
    losses = []
    for _ in range(60):
        x, y = _synthetic_batch(rng)
        (out,) = exe.run(main, feed={"img": x, "label": y},
                         fetch_list=[avg_loss])
        losses.append(float(np.asarray(out).reshape(-1)[0]))
    assert losses[-1] < losses[0] * 0.5


def test_momentum_and_weight_decay():
    main, startup, avg_loss = _mnist_fc_program()
    with fluid.program_guard(main, startup):
        opt = fluid.optimizer.Momentum(
            learning_rate=0.1, momentum=0.9,
            regularization=fluid.regularizer.L2Decay(1e-4))
        opt.minimize(avg_loss)
    exe = fluid.Executor()
    exe.run(startup)
    rng = np.random.RandomState(3)
    first = last = None
    for i in range(40):
        x, y = _synthetic_batch(rng)
        (out,) = exe.run(main, feed={"img": x, "label": y},
                         fetch_list=[avg_loss])
        if i == 0:
            first = float(np.asarray(out).reshape(-1)[0])
        last = float(np.asarray(out).reshape(-1)[0])
    assert last < first


def test_fetch_without_feed_reads_scope():
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        w = fluid.layers.create_parameter([3, 3], "float32")
    exe = fluid.Executor()
    exe.run(startup)
    (val,) = exe.run(main, fetch_list=[w])
    assert val.shape == (3, 3)


def test_uninitialized_run_raises():
    main, startup, avg_loss = _mnist_fc_program()
    exe = fluid.Executor()
    rng = np.random.RandomState(0)
    x, y = _synthetic_batch(rng, 8)
    with pytest.raises(RuntimeError, match="initialization"):
        exe.run(main, feed={"img": x, "label": y},
                fetch_list=[avg_loss])


def test_program_clone_for_test_freezes_dropout():
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        img = fluid.layers.data("img", shape=[16], dtype="float32")
        h = fluid.layers.dropout(img, 0.5)
        out = fluid.layers.fc(h, 4)
    test_prog = main.clone(for_test=True)
    drop_ops = [op for op in test_prog.global_block.ops
                if op.type == "dropout"]
    assert drop_ops and all(op.attrs["is_test"] for op in drop_ops)
    # original program untouched
    drop_ops = [op for op in main.global_block.ops
                if op.type == "dropout"]
    assert all(not op.attrs["is_test"] for op in drop_ops)


def test_batch_norm_updates_running_stats():
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        img = fluid.layers.data("img", shape=[3, 8, 8], dtype="float32")
        out = fluid.layers.batch_norm(img)
        loss = fluid.layers.mean(out)
    exe = fluid.Executor()
    exe.run(startup)
    bn_mean_name = [v.name for v in main.global_block.vars.values()
                    if "batch_norm" in v.name and v.persistable][0]
    scope = fluid.global_scope()
    x = np.random.RandomState(0).rand(4, 3, 8, 8).astype("float32") + 5.0
    exe.run(main, feed={"img": x}, fetch_list=[loss])
    mean_names = [n for n in main.global_block.vars
                  if n.endswith("global_0")]
    # running mean must have moved off zero after one train step
    moved = False
    for v in main.global_block.vars.values():
        if v.persistable and v.shape == (3,):
            val = np.asarray(scope._get(v.name))
            if val is not None and np.abs(val).max() > 1e-3:
                moved = True
    assert moved


def test_gradient_accumulation_shared_param():
    """A param used twice must receive the SUM of both grads
    (backward.py dedup path, reference _addup_repetitive_outputs_)."""
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4], dtype="float32")
        w = fluid.layers.create_parameter(
            [4, 4], "float32", attr=fluid.ParamAttr(name="w_sh"))
        a = fluid.layers.mul(x, w)
        b = fluid.layers.mul(x, w)
        y = fluid.layers.elementwise_add(a, b)
        loss = fluid.layers.mean(y)
        from paddle_tpu.backward import append_backward

        pg = append_backward(loss)
    exe = fluid.Executor()
    exe.run(startup)
    xv = np.ones((2, 4), dtype="float32")
    grad_name = [g.name for p, g in pg if p.name == "w_sh"][0]
    (gw,) = exe.run(main, feed={"x": xv}, fetch_list=[grad_name])
    # d/dw mean(2 * x@w) = 2 * x^T @ ones / (2*4)
    expect = 2 * xv.T @ np.ones((2, 4), "float32") / 8.0
    np.testing.assert_allclose(gw, expect, rtol=1e-5)
