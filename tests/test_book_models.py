"""Book-model parity: recommender system + label semantic roles
(reference tests/book/test_recommender_system.py,
test_label_semantic_roles.py) train end to end with decreasing loss.
"""
import numpy as np

import paddle_tpu as fluid
from paddle_tpu.models import label_semantic_roles as srl
from paddle_tpu.models import recommender as rec


def _run(prog, startup, cost, feeds, steps=12, scope=None,
         return_exe=False):
    """Shared book-model train loop (also used by test_book_models2)."""
    exe = fluid.Executor(fluid.CPUPlace())
    if scope is None:
        scope = fluid.Scope()  # fresh per call (book1 tests rely on it)
    kw = {"scope": scope}
    exe.run(startup, **kw)
    losses = []
    for _ in range(steps):
        l, = exe.run(prog, feed=feeds, fetch_list=[cost], **kw)
        losses.append(float(np.asarray(l).reshape(-1)[0]))
    return (exe, losses) if return_exe else losses


class TestRecommenderSystem:
    def test_trains(self):
        rng = np.random.RandomState(0)
        b, tl = 16, 8
        prog, startup, cost, infer = rec.build_program(title_len=tl)
        cat_len = rng.randint(1, 4, (b,)).astype(np.int32)
        title_len = rng.randint(2, tl + 1, (b,)).astype(np.int32)
        feeds = {
            "user_id": rng.randint(0, rec.USR_DICT, (b, 1))
            .astype(np.int64),
            "gender_id": rng.randint(0, 2, (b, 1)).astype(np.int64),
            "age_id": rng.randint(0, rec.AGE_DICT, (b, 1))
            .astype(np.int64),
            "job_id": rng.randint(0, rec.JOB_DICT, (b, 1))
            .astype(np.int64),
            "movie_id": rng.randint(0, rec.MOV_DICT, (b, 1))
            .astype(np.int64),
            "category_id": rng.randint(0, rec.CATEGORY_DICT,
                                       (b, rec.CATEGORY_DICT))
            .astype(np.int64),
            "category_id@SEQ_LEN": cat_len,
            "movie_title": rng.randint(0, rec.TITLE_DICT, (b, tl))
            .astype(np.int64),
            "movie_title@SEQ_LEN": title_len,
            "score": rng.uniform(1, 5, (b, 1)).astype(np.float32),
        }
        losses = _run(prog, startup, cost, feeds, steps=15)
        assert losses[-1] < losses[0] * 0.8, losses

    def test_inference_range(self):
        rng = np.random.RandomState(1)
        prog, startup, cost, infer = rec.build_program(
            with_optimizer=False, title_len=4)
        test_prog = prog.clone(for_test=True)
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.Scope()
        exe.run(startup, scope=scope)
        b = 4
        feeds = {
            "user_id": np.zeros((b, 1), np.int64),
            "gender_id": np.zeros((b, 1), np.int64),
            "age_id": np.zeros((b, 1), np.int64),
            "job_id": np.zeros((b, 1), np.int64),
            "movie_id": np.zeros((b, 1), np.int64),
            "category_id": np.zeros((b, rec.CATEGORY_DICT), np.int64),
            "category_id@SEQ_LEN": np.ones((b,), np.int32),
            "movie_title": np.zeros((b, 4), np.int64),
            "movie_title@SEQ_LEN": np.full((b,), 4, np.int32),
            "score": np.ones((b, 1), np.float32),
        }
        out, = exe.run(test_prog, feed=feeds, fetch_list=[infer],
                       scope=scope)
        assert np.all(np.abs(out) <= 5.0 + 1e-5)  # cos_sim * 5


def _srl_feeds(rng, b, t, lens, target=None):
    feeds = {}
    for name in srl.FEATURES + ("verb_data", "mark_data"):
        dict_size = {"verb_data": srl.PRED_DICT,
                     "mark_data": srl.MARK_DICT}.get(
            name, srl.WORD_DICT)
        feeds[name] = rng.randint(0, dict_size, (b, t)).astype(
            np.int64)
        feeds[name + "@SEQ_LEN"] = lens
    feeds["target"] = (target if target is not None else
                       rng.randint(0, srl.LABEL_DICT,
                                   (b, t)).astype(np.int64))
    feeds["target@SEQ_LEN"] = lens
    return feeds


class TestLabelSemanticRoles:
    def test_crf_trains(self):
        rng = np.random.RandomState(0)
        b, t = 8, 12
        prog, startup, cost, decode = srl.build_program(
            seq_len=t, depth=2, lr=0.02)
        lens = rng.randint(t // 2, t + 1, (b,)).astype(np.int32)
        feeds = _srl_feeds(rng, b, t, lens)
        losses = _run(prog, startup, cost, feeds, steps=12)
        assert losses[-1] < losses[0], losses

    def test_padding_does_not_affect_cost(self):
        # same valid prefix, different garbage in the padded tail ->
        # identical CRF cost (the length wiring the review demanded)
        rng = np.random.RandomState(3)
        b, t = 4, 10
        prog, startup, cost, _ = srl.build_program(
            seq_len=t, depth=2, with_optimizer=False)
        lens = np.full((b,), 6, np.int32)
        feeds = _srl_feeds(rng, b, t, lens)
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.Scope()
        exe.run(startup, scope=scope)
        c1, = exe.run(prog, feed=feeds, fetch_list=[cost], scope=scope)
        tgt2 = feeds["target"].copy()
        tgt2[:, 6:] = (tgt2[:, 6:] + 7) % srl.LABEL_DICT
        feeds2 = dict(feeds, target=tgt2)
        c2, = exe.run(prog, feed=feeds2, fetch_list=[cost],
                      scope=scope)
        np.testing.assert_allclose(np.asarray(c1), np.asarray(c2),
                                   rtol=1e-6)

    def test_decode_shape(self):
        rng = np.random.RandomState(2)
        b, t = 4, 10
        prog, startup, cost, decode = srl.build_program(
            seq_len=t, depth=2, with_optimizer=False)
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.Scope()
        exe.run(startup, scope=scope)
        lens = np.full((b,), t, np.int32)
        feeds = _srl_feeds(rng, b, t, lens,
                           target=np.zeros((b, t), np.int64))
        path, = exe.run(prog, feed=feeds, fetch_list=[decode],
                        scope=scope)
        assert path.shape == (b, t)
        assert path.min() >= 0 and path.max() < srl.LABEL_DICT
