"""Sharding domain tests (absint ShardSpec propagation + the PTA160/
PTA161 provers + the tp-sharded decoder fixture).

The property suite pins each registered rule family against WHAT XLA
ACTUALLY DOES: the same computation runs under jax.jit on the virtual
8-device mesh with NamedSharding inputs, and the rule's propagated
output spec must equal the sharding GSPMD chose for the real output
(conftest.py provides the 4x2 dp/tp mesh). That keeps the static
algebra honest — a rule drifting from GSPMD's behavior fails here,
not in a wrong memory plan or a missed deadlock.
"""
import warnings

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.analysis import ERROR, WARNING, absint, run_checks
from paddle_tpu.analysis.absint import (MeshConfig, REPLICATED_SPEC,
                                        ShardSpec, TOP_SPEC)


def _diags(program, code):
    return [d for d in run_checks(program) if d.code == code]


def _guarded():
    main, startup = fluid.Program(), fluid.Program()
    return main, startup, fluid.program_guard(main, startup)


MESH = MeshConfig.make(dp=4, tp=2)


def _data(name, shape, placements=None, dtype="float32"):
    v = layers.data(name, shape=list(shape), dtype=dtype,
                    append_batch_size=False)
    if placements:
        absint.mark_sharded(v, placements)
    return v


def _spec_to_pspec(spec, rank):
    """ShardSpec -> jax PartitionSpec-equivalent tuple of axis names."""
    return tuple(spec.axis_of(d) for d in range(rank))


def _jax_out_pspec(fn, in_arrays, in_pspecs, out_rank):
    """What GSPMD actually picks for fn's output under these input
    shardings, padded to out_rank."""
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec

    mesh = Mesh(np.array(jax.devices()[:8]).reshape(4, 2),
                ("dp", "tp"))
    put = [jax.device_put(a, NamedSharding(mesh, PartitionSpec(*p)))
           for a, p in zip(in_arrays, in_pspecs)]
    out = jax.jit(fn)(*put)
    got = tuple(out.sharding.spec)
    return got + (None,) * (out_rank - len(got))


# ---------------------------------------------------------------------------
# spec / mesh primitives
# ---------------------------------------------------------------------------
class TestSpecPrimitives:
    def test_spec_normalization_and_describe(self):
        s = ShardSpec.of({1: "tp", 0: "dp"})
        assert s.placements == ((0, "dp"), (1, "tp"))
        assert s.describe() == "dim0:dp,dim1:tp"
        assert REPLICATED_SPEC.is_replicated
        assert TOP_SPEC.is_top and TOP_SPEC.describe() == "⊤"

    def test_spec_join(self):
        a = ShardSpec.of({0: "dp"})
        assert absint.spec_join(a, a) == a
        assert absint.spec_join(a, REPLICATED_SPEC).is_top
        assert absint.spec_join(a, TOP_SPEC).is_top

    def test_mesh_config(self):
        assert MESH.size("tp") == 2
        assert MESH.size("nope") == 1
        assert MESH.n_devices() == 8
        assert MESH.describe() == "dp=4xtp=2"

    def test_set_mesh_bumps_version(self):
        p = fluid.Program()
        v0 = p._version
        absint.set_mesh(p, MESH)
        assert p._version > v0
        assert absint.mesh_of(p) == MESH

    def test_clone_carries_mesh_and_budget(self):
        # Program.clone keeps the analysis-layer program attrs, like
        # it keeps var annotations and op _uids: an eval/serving
        # clone must not silently lose its mesh (per-device plans)
        # or its OOM-gate budget
        main, startup, g = _guarded()
        with g:
            x = _data("x", (8, 16), {1: "tp"})
            layers.fc(x, size=4)
        absint.set_mesh(main, MESH)
        absint.set_device_memory_budget(main, 12345)
        clone = main.clone(for_test=True)
        assert absint.mesh_of(clone) == MESH
        assert absint.device_memory_budget(clone) == 12345


# ---------------------------------------------------------------------------
# mark_sharded: dict placements, legacy axes, producer-less vars
# ---------------------------------------------------------------------------
class TestMarkSharded:
    def test_producerless_data_var_seeds_spec(self):
        # the sharded-serving ENTRY POINT: feeds have no producer op,
        # and the annotation must still seed both domains
        main, startup, g = _guarded()
        with g:
            x = _data("x", (8, 16), {0: "dp"})
            h = layers.scale(x, 2.0)
        facts = absint.analyze(main)
        assert facts.spec(x.name) == ShardSpec.of({0: "dp"})
        assert facts.value(x.name).repl == absint.VARYING
        # and it propagates
        assert facts.spec(h.name) == ShardSpec.of({0: "dp"})

    def test_producerless_parameter_seeds_spec(self):
        main, startup, g = _guarded()
        with g:
            w = main.global_block.create_parameter(
                name="tt_w", shape=[16, 8], dtype="float32")
            absint.mark_sharded(w, {1: "tp"})
            x = _data("x", (4, 16))
            main.global_block.append_op(
                "mul", {"X": [x.name], "Y": [w.name]},
                {"Out": ["o"]}, {"x_num_col_dims": 1,
                                 "y_num_col_dims": 1})
        facts = absint.analyze(main)
        assert facts.spec("o") == ShardSpec.of({1: "tp"})

    def test_legacy_axes_form_still_marks_varying(self):
        main, startup, g = _guarded()
        with g:
            x = _data("x", (8,))
            h = layers.scale(x, 1.0)
            absint.mark_sharded(h, ("model",))
        facts = absint.analyze(main)
        assert facts.value(h.name).sharded == ("model",)
        # dims unknown: the spec domain pins the explicit ⊤
        assert facts.spec(h.name).is_top

    def test_negative_dim_resolves_against_rank(self):
        main, startup, g = _guarded()
        with g:
            x = _data("x", (8, 16), {-1: "tp"})
        facts = absint.analyze(main)
        assert facts.spec(x.name) == ShardSpec.of({1: "tp"})

    def test_out_of_range_dim_refused(self):
        main, startup, g = _guarded()
        with g:
            x = _data("x", (8, 16))
            with pytest.raises(ValueError, match="out of range"):
                absint.mark_sharded(x, {5: "tp"})

    def test_nameless_string_refused(self):
        with pytest.raises(ValueError, match="neither"):
            absint.mark_sharded("just_a_name", {0: "dp"})


# ---------------------------------------------------------------------------
# property tests: rule output == GSPMD's actual choice, per family
# ---------------------------------------------------------------------------
class TestRulesMatchGSPMD:
    """Each case builds the op through the REAL layer path, seeds
    input placements, and compares the propagated spec with the
    sharding jax.jit+GSPMD picks for the identical computation on the
    identical mesh."""

    def _propagated(self, main, out_var):
        absint.set_mesh(main, MESH)
        facts = absint.analyze(main)
        assert facts.converged
        return facts.spec(out_var.name)

    def test_elementwise_add(self):
        main, startup, g = _guarded()
        with g:
            x = _data("x", (8, 16), {0: "dp"})
            y = _data("y", (8, 16))
            out = layers.elementwise_add(x, y)
        spec = self._propagated(main, out)
        want = _jax_out_pspec(
            lambda a, b: a + b,
            [np.zeros((8, 16), np.float32)] * 2,
            [("dp", None), (None, None)], 2)
        assert _spec_to_pspec(spec, 2) == want == ("dp", None)

    def test_transpose(self):
        main, startup, g = _guarded()
        with g:
            x = _data("x", (8, 16), {0: "dp"})
            out = layers.transpose(x, perm=[1, 0])
        spec = self._propagated(main, out)
        want = _jax_out_pspec(
            lambda a: a.T, [np.zeros((8, 16), np.float32)],
            [("dp", None)], 2)
        assert _spec_to_pspec(spec, 2) == want == (None, "dp")

    def test_reduce_unsharded_dim_keeps_placement(self):
        main, startup, g = _guarded()
        with g:
            x = _data("x", (8, 16), {0: "dp"})
            out = layers.reduce_sum(x, dim=1)
        spec = self._propagated(main, out)
        want = _jax_out_pspec(
            lambda a: a.sum(1), [np.zeros((8, 16), np.float32)],
            [("dp", None)], 1)
        assert _spec_to_pspec(spec, 1) == want == ("dp",)

    def test_reduce_sharded_dim_replicates_and_implies_psum(self):
        main, startup, g = _guarded()
        with g:
            x = _data("x", (8, 16), {0: "dp"})
            out = layers.reduce_sum(x, dim=0)
        absint.set_mesh(main, MESH)
        facts = absint.analyze(main)
        spec = facts.spec(out.name)
        want = _jax_out_pspec(
            lambda a: a.sum(0), [np.zeros((8, 16), np.float32)],
            [("dp", None)], 1)
        assert _spec_to_pspec(spec, 1) == want == (None,)
        psums = [es for es in facts.collective_events
                 if es.event.kind == "psum"]
        assert psums and psums[0].event.axes == ("dp",)

    def test_matmul_batch_row_sharded(self):
        main, startup, g = _guarded()
        with g:
            x = _data("x", (8, 16), {0: "dp"})
            w = _data("w", (16, 4))
            out = layers.matmul(x, w)
        spec = self._propagated(main, out)
        want = _jax_out_pspec(
            lambda a, b: a @ b,
            [np.zeros((8, 16), np.float32),
             np.zeros((16, 4), np.float32)],
            [("dp", None), (None, None)], 2)
        assert _spec_to_pspec(spec, 2) == want == ("dp", None)

    def test_matmul_contraction_sharded_row_parallel(self):
        main, startup, g = _guarded()
        with g:
            x = _data("x", (8, 16), {1: "tp"})
            w = _data("w", (16, 4), {0: "tp"})
            out = layers.matmul(x, w)
        absint.set_mesh(main, MESH)
        facts = absint.analyze(main)
        spec = facts.spec(out.name)
        want = _jax_out_pspec(
            lambda a, b: a @ b,
            [np.zeros((8, 16), np.float32),
             np.zeros((16, 4), np.float32)],
            [(None, "tp"), ("tp", None)], 2)
        assert _spec_to_pspec(spec, 2) == want == (None, None)
        psums = [es for es in facts.collective_events
                 if es.event.kind == "psum"]
        assert psums and psums[0].event.axes == ("tp",)

    def test_matmul_column_parallel(self):
        main, startup, g = _guarded()
        with g:
            x = _data("x", (8, 16))
            w = _data("w", (16, 4), {1: "tp"})
            out = layers.matmul(x, w)
        spec = self._propagated(main, out)
        want = _jax_out_pspec(
            lambda a, b: a @ b,
            [np.zeros((8, 16), np.float32),
             np.zeros((16, 4), np.float32)],
            [(None, None), (None, "tp")], 2)
        assert _spec_to_pspec(spec, 2) == want == (None, "tp")

    def test_reshape_split_carries_major_dim(self):
        main, startup, g = _guarded()
        with g:
            x = _data("x", (8, 16), {1: "tp"})
            out = layers.reshape(x, [8, 4, 4])
        spec = self._propagated(main, out)
        want = _jax_out_pspec(
            lambda a: a.reshape(8, 4, 4),
            [np.zeros((8, 16), np.float32)], [(None, "tp")], 3)
        assert _spec_to_pspec(spec, 3) == want == (None, "tp", None)

    def test_reshape_merge_carries_major_dim(self):
        main, startup, g = _guarded()
        with g:
            x = _data("x", (8, 4, 4), {1: "tp"})
            out = layers.reshape(x, [8, 16])
        spec = self._propagated(main, out)
        want = _jax_out_pspec(
            lambda a: a.reshape(8, 16),
            [np.zeros((8, 4, 4), np.float32)], [(None, "tp", None)],
            2)
        assert _spec_to_pspec(spec, 2) == want == (None, "tp")

    def test_softmax_keeps_layout(self):
        main, startup, g = _guarded()
        with g:
            x = _data("x", (8, 16), {1: "tp"})
            out = layers.softmax(x, axis=-1)
        spec = self._propagated(main, out)
        import jax

        want = _jax_out_pspec(
            lambda a: jax.nn.softmax(a, -1),
            [np.zeros((8, 16), np.float32)], [(None, "tp")], 2)
        assert _spec_to_pspec(spec, 2) == want == (None, "tp")

    def test_argmax_over_sharded_dim_replicates(self):
        main, startup, g = _guarded()
        with g:
            x = _data("x", (8, 16), {1: "tp"})
            out = layers.argmax(x, axis=-1)
        absint.set_mesh(main, MESH)
        facts = absint.analyze(main)
        import jax.numpy as jnp

        want = _jax_out_pspec(
            lambda a: jnp.argmax(a, -1),
            [np.zeros((8, 16), np.float32)], [(None, "tp")], 1)
        assert _spec_to_pspec(facts.spec(out.name), 1) == want \
            == (None,)
        gathers = [es for es in facts.collective_events
                   if es.event.kind == "allgather"]
        assert gathers and gathers[0].event.axes == ("tp",)

    def test_squeeze_shifts_placement_down(self):
        # the [B,1,D] {2:tp} -> squeeze axes=[1] case: the placement
        # legitimately lands ON the squeezed position after the
        # shift and must survive (regression: an over-eager filter
        # dropped it to replicated)
        main, startup, g = _guarded()
        with g:
            x = _data("x", (8, 1, 16), {2: "tp"})
            out = layers.squeeze(x, axes=[1])
        absint.set_mesh(main, MESH)
        facts = absint.analyze(main)
        import jax.numpy as jnp

        want = _jax_out_pspec(
            lambda a: jnp.squeeze(a, 1),
            [np.zeros((8, 1, 16), np.float32)],
            [(None, None, "tp")], 2)
        assert _spec_to_pspec(facts.spec(out.name), 2) == want \
            == (None, "tp")

    def test_squeeze_of_sharded_dim_degrades_to_top(self):
        main, startup, g = _guarded()
        with g:
            x = _data("x", (8, 1, 16), {1: "tp"})
            out = layers.squeeze(x, axes=[1])
        absint.set_mesh(main, MESH)
        assert absint.analyze(main).spec(out.name).is_top

    def test_unknown_op_degrades_to_top_and_warns_once(self):
        main, startup, g = _guarded()
        with g:
            x = _data("x", (8, 16), {0: "dp"})
            main.global_block.append_op(
                "_no_rule_op_xyz", {"X": [x.name]}, {"Out": ["o"]}, {})
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            facts = absint.analyze(main)
        assert facts.spec("o").is_top
        msgs = [w for w in caught
                if "no registered sharding rule" in str(w.message)]
        assert msgs and "_no_rule_op_xyz" in str(msgs[0].message)

    def test_unknown_op_with_replicated_inputs_stays_replicated(self):
        main, startup, g = _guarded()
        with g:
            x = _data("x", (8, 16))
            main.global_block.append_op(
                "_no_rule_op_xyz2", {"X": [x.name]}, {"Out": ["o"]},
                {})
        facts = absint.analyze(main)
        assert facts.spec("o").is_replicated


# ---------------------------------------------------------------------------
# head-interleaved fused qkv (r19 satellite): the exact decomposition
# chain cached_decoder_step builds, pinned against GSPMD
# ---------------------------------------------------------------------------
class TestInterleavedQKV:
    """The r17 leftover closed by ``qkv_interleaved``: with the fused
    qkv columns ``[H, 3, Dh]``-major, a dim-1 column shard on the
    weight must carry through matmul → reshape (major-carry onto the
    HEAD axis) → split on the local 3-axis → squeeze → transpose and
    land head-sharded, with zero reshard events.  The contiguous
    ``[3, H, Dh]``-major layout fails at the very first split (it
    slices ACROSS tp shard boundaries) — which is why it deliberately
    stays replicated (ShardingConfig docstring)."""

    R, D, H, DH = 8, 16, 4, 4  # 3D = 48, tp=2 divides H

    def _chain(self, interleaved):
        """Build cached_decoder_step's qkv decomposition through the
        real layer path; returns (main, out_var, facts)."""
        main, startup, g = _guarded()
        R, D, H, DH = self.R, self.D, self.H, self.DH
        with g:
            x = _data("x", (R, 1, D))
            w = _data("w", (D, 3 * D), {1: "tp"})
            qkv = layers.matmul(x, w)  # [R,1,3D]
            if interleaved:
                z = layers.reshape(qkv, [R, 1, H, 3, DH])
                zq = layers.split(z, 3, dim=3)[0]
                out = layers.transpose(layers.squeeze(zq, axes=[3]),
                                       perm=[0, 2, 1, 3])
            else:
                qv = layers.split(qkv, 3, dim=2)[0]  # [R,1,D]
                z = layers.reshape(qv, [R, 1, H, DH])
                out = layers.transpose(z, perm=[0, 2, 1, 3])
        absint.set_mesh(main, MESH)
        facts = absint.analyze(main)
        assert facts.converged
        return main, out, facts

    def test_interleaved_carries_head_shard_matches_gspmd(self):
        import jax.numpy as jnp

        R, D, H, DH = self.R, self.D, self.H, self.DH
        _, out, facts = self._chain(interleaved=True)
        spec = facts.spec(out.name)

        def fn(a, b):
            z = (a @ b).reshape(R, 1, H, 3, DH)
            zq = jnp.split(z, 3, axis=3)[0]
            return jnp.transpose(jnp.squeeze(zq, 3), (0, 2, 1, 3))

        want = _jax_out_pspec(
            fn,
            [np.zeros((R, 1, D), np.float32),
             np.zeros((D, 3 * D), np.float32)],
            [(None, None, None), (None, "tp")], 4)
        assert _spec_to_pspec(spec, 4) == want == \
            (None, "tp", None, None)
        # the whole decomposition is LOCAL under the column shard
        assert not [es for es in facts.collective_events
                    if es.event.kind == "reshard"]

    def test_contiguous_split_forces_reshard(self):
        _, out, facts = self._chain(interleaved=False)
        # the fused-axis split crosses tp shard boundaries: the rule
        # records the forced reshard and drops the placement — the
        # reason the contiguous layout ships replicated
        reshards = [es for es in facts.collective_events
                    if es.event.kind == "reshard"]
        assert reshards and reshards[0].event.axes == ("tp",)
        assert facts.spec(out.name).axes() == ()


# ---------------------------------------------------------------------------
# PTA160: sharding contradiction / implicit reshard
# ---------------------------------------------------------------------------
class TestPTA160:
    def test_conflicting_operands_warn_at_top_level(self):
        main, startup, g = _guarded()
        with g:
            x = _data("x", (8, 16), {0: "dp"})
            y = _data("y", (8, 16), {0: "tp"})
            layers.elementwise_add(x, y)
        ds = _diags(main, "PTA160")
        assert ds and ds[0].severity == WARNING
        assert "incompatible specs" in ds[0].message

    def test_conflict_inside_while_is_error(self):
        main, startup, g = _guarded()
        with g:
            x = _data("x", (8, 16), {0: "dp"})
            y = _data("y", (8, 16), {0: "tp"})
            i = layers.fill_constant([1], "int64", 0)
            limit = layers.fill_constant([1], "int64", 4)
            cond = layers.less_than(i, limit)
            w = layers.While(cond)
            with w.block():
                layers.elementwise_add(x, y)
                layers.increment(i, 1)
                layers.less_than(i, limit, cond=cond)
        ds = _diags(main, "PTA160")
        assert ds and ds[0].severity == ERROR
        assert "INSIDE the loop" in ds[0].message

    def test_pin_disagreement_in_while_is_error(self):
        # the r5 family: state pinned to a placement, a loop body
        # writing it replicated — GSPMD reshards every iteration
        main, startup, g = _guarded()
        with g:
            acc = main.global_block.create_var(
                name="@acc160", shape=(8, 16), dtype="float32",
                persistable=True, stop_gradient=True)
            absint.mark_sharded(acc, {0: "dp"})
            x = _data("x", (8, 16))
            i = layers.fill_constant([1], "int64", 0)
            limit = layers.fill_constant([1], "int64", 4)
            cond = layers.less_than(i, limit)
            w = layers.While(cond)
            with w.block():
                layers.assign(layers.scale(x, 2.0), output=acc)
                layers.increment(i, 1)
                layers.less_than(i, limit, cond=cond)
        ds = _diags(main, "PTA160")
        assert ds and ds[0].severity == ERROR
        assert "pinned" in ds[0].message

    def test_top_level_reshard_is_silent_but_recorded(self):
        # a one-off layout change in straight-line code is a fact
        # for the planner, not a diagnostic
        main, startup, g = _guarded()
        with g:
            acc = main.global_block.create_var(
                name="@acc160b", shape=(8, 16), dtype="float32",
                persistable=True, stop_gradient=True)
            absint.mark_sharded(acc, {0: "dp"})
            x = _data("x", (8, 16))
            layers.assign(layers.scale(x, 2.0), output=acc)
        assert not _diags(main, "PTA160")
        facts = absint.analyze(main)
        assert any(es.event.kind == "reshard"
                   for es in facts.collective_events)

    def test_consistent_sharding_is_clean(self):
        main, startup, g = _guarded()
        with g:
            x = _data("x", (8, 16), {0: "dp"})
            y = _data("y", (8, 16), {0: "dp"})
            layers.elementwise_add(x, y)
        assert not _diags(main, "PTA160")


# ---------------------------------------------------------------------------
# PTA161: collective-order agreement (the 1F1B x tp corollary)
# ---------------------------------------------------------------------------
def _vocab_psum_under_stage_cond():
    """THE r5 shape, rebuilt from sharding facts alone: a per-STAGE
    predicate (pp_stage_id divergence source) gating a branch whose
    body contracts a tp-sharded dim — the Megatron vocab head's psum,
    landing inside divergent control flow. No collective op appears
    anywhere; the psum exists only as a consequence of the layout,
    which is exactly what the pattern matchers could never see."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[8], dtype="float32")
        w = main.global_block.create_parameter(
            name="vocab_head.w", shape=[8, 16], dtype="float32")
        absint.mark_sharded(w, {0: "tp"})
        absint.set_mesh(main, MeshConfig.make(pp=2, tp=2))
        stage = layers.fill_constant([1], "float32", 0.0)
        absint.mark_divergence_source(stage, "pp_stage_id")
        pred = layers.less_than_value(stage, 1.0)
        sub = main.create_block()
        sub.append_op("mul", {"X": [x.name], "Y": [w.name]},
                      {"Out": ["logits"]},
                      {"x_num_col_dims": 1, "y_num_col_dims": 1})
        main.rollback()
        fsub = main.create_block()
        fsub.append_op("scale", {"X": [x.name]}, {"Out": ["noop"]},
                       {"scale": 1.0})
        main.rollback()
        main.global_block.append_op(
            "conditional_block",
            {"Condition": [pred.name], "X": [x.name, w.name]},
            {"Out": ["b_out"]},
            {"true_block": sub, "false_block": fsub,
             "true_out": "logits", "false_out": "noop"})
    return main


class TestPTA161:
    def test_1f1b_x_tp_rejection_rederived(self):
        """The acceptance pin: the 1F1B x tp vocab-psum rejection
        (pipeline_1f1b.py's named ValueError) falls out of the
        collective-order PROOF — divergence source named, mesh axis
        named, observed sequences enumerated — with no schedule-
        specific special case anywhere."""
        main = _vocab_psum_under_stage_cond()
        ds = _diags(main, "PTA161")
        assert ds and ds[0].severity == ERROR
        msg = ds[0].message
        assert "pp_stage_id" in msg          # the divergence source
        assert "'tp'" in msg or "tp" in msg  # the collective's axis
        assert "disagree" in msg and "deadlock" in msg
        assert "observe" in msg              # the sequence proof

    def test_same_psum_at_top_level_is_silent(self):
        main, startup, g = _guarded()
        with g:
            x = layers.data("x", shape=[8], dtype="float32")
            w = main.global_block.create_parameter(
                name="vh2.w", shape=[8, 16], dtype="float32")
            absint.mark_sharded(w, {0: "tp"})
            main.global_block.append_op(
                "mul", {"X": [x.name], "Y": [w.name]},
                {"Out": ["logits"]},
                {"x_num_col_dims": 1, "y_num_col_dims": 1})
        assert not _diags(main, "PTA161")

    def test_unprovable_guard_is_warning(self):
        # a guard whose predicate the replication facts cannot
        # classify: order agreement is unverifiable, not disproven
        main, startup, g = _guarded()
        with g:
            x = _data("x", (8, 16), {1: "tp"})
            sub = main.create_block()
            sub.append_op("reduce_sum", {"X": [x.name]},
                          {"Out": ["s"]}, {"dim": [1]})
            main.rollback()
            # a while with NO Condition slot: the guard classifies
            # UNKNOWN (nothing to prove uniform)
            main.global_block.append_op(
                "while", {"X": [], "Init": []}, {"Out": []},
                {"sub_block": sub, "carried": [], "externals": []})
        ds = _diags(main, "PTA161")
        assert ds and ds[0].severity == WARNING
        assert "cannot be verified" in ds[0].message

    def test_uniform_guard_is_silent(self):
        main, startup, g = _guarded()
        with g:
            x = _data("x", (8, 16), {1: "tp"})
            i = layers.fill_constant([1], "int64", 0)
            limit = layers.fill_constant([1], "int64", 4)
            cond = layers.less_than(i, limit)
            w = layers.While(cond)
            with w.block():
                layers.reduce_sum(x, dim=1)
                layers.increment(i, 1)
                layers.less_than(i, limit, cond=cond)
        assert not _diags(main, "PTA161")

    def test_mixed_manual_and_sharded_guard_stays_divergent(self):
        """The GSPMD-uniform reclassification must NOT fire for a
        predicate that mixes sharded values with a MANUAL divergence
        source: the sticky ValueFact.manual bit survives joins even
        when the sharded operand comes FIRST and its 'sharding:*'
        source string wins the join — a psum under such a guard is
        still a proven deadlock."""
        main, startup, g = _guarded()
        with g:
            x = _data("x", (8, 16), {1: "tp"})
            stage = layers.fill_constant([1], "float32", 0.0)
            absint.mark_divergence_source(stage, "pp_stage_id")
            # sharded ancestry FIRST, manual second: the joined
            # fact's source string is the sharding one
            sx = layers.reduce_sum(x, dim=1)          # varying: tp
            mixed = layers.elementwise_add(
                layers.reduce_sum(sx, dim=0, keep_dim=True), stage)
            one = layers.fill_constant([1], "float32", 1.0)
            cond = layers.less_than(mixed, one)
            w = layers.While(cond)
            with w.block():
                layers.reduce_sum(x, dim=1)  # implied psum in body
                layers.less_than(mixed, one, cond=cond)
        ds = _diags(main, "PTA161")
        assert ds and ds[0].severity == ERROR
        assert "pp_stage_id" in ds[0].message


# ---------------------------------------------------------------------------
# paged/spec op families vs GSPMD's ACTUAL choice (the r17 satellite:
# an unregistered op blinds PTA160/161 and inflates the PTA170 plan on
# exactly the sharded serve programs — these pin each family's rule
# against what XLA does on the 8-dev mesh)
# ---------------------------------------------------------------------------
class TestPagedSpecOpRules:
    def _facts(self, main):
        return absint.analyze(main)

    def test_masked_pool_write_keeps_pool_layout(self):
        NB, BS, H, Dh, R = 8, 4, 4, 4, 5
        main, startup, g = _guarded()
        with g:
            pool = main.global_block.create_var(
                name="@rulepool", shape=(NB, BS, H, Dh),
                dtype="float32", persistable=True,
                stop_gradient=True)
            absint.mark_sharded(pool, {2: "tp"})
            new = _data("new", (R, H, Dh))
            idx = _data("idx", (R,), dtype="int64")
            gate = _data("gate", (R,))
            layers.masked_pool_write(pool, new, idx, gate=gate,
                                     leading_dims=2,
                                     exclusive_via="block_table")
        facts = self._facts(main)
        assert facts.spec("@rulepool") == ShardSpec.of({2: "tp"})
        # replicated New into a sharded pool is a local slice — the
        # rule must NOT claim a reshard (free under GSPMD)
        assert not [es for es in facts.collective_events
                    if es.event.kind == "reshard"]

        import jax.numpy as jnp

        def fn(pool, new, idx, gate):
            n = NB * BS
            pf = pool.reshape(n, -1)
            nf = new.reshape(R, -1).astype(pf.dtype)
            ii = idx.reshape(R).astype(jnp.int32)
            keep = (ii >= 0) & (ii < n) & (gate.reshape(R) > 0)
            safe = jnp.where(keep, ii, n)
            padded = jnp.concatenate(
                [pf, jnp.zeros((1,) + pf.shape[1:], pf.dtype)], 0)
            return padded.at[safe].set(nf)[:n].reshape(pool.shape)

        got = _jax_out_pspec(
            fn,
            [np.zeros((NB, BS, H, Dh), np.float32),
             np.ones((R, H, Dh), np.float32),
             np.arange(R, dtype=np.int32), np.ones(R, np.float32)],
            [(None, None, "tp", None), (), (), ()], 4)
        assert got == _spec_to_pspec(facts.spec("@rulepool"), 4)

    def test_span_scatter_keeps_buffer_layout(self):
        R, T, W = 8, 16, 4
        main, startup, g = _guarded()
        with g:
            buf = main.global_block.create_var(
                name="@rulebuf", shape=(R, T), dtype="int64",
                persistable=True, stop_gradient=True)
            absint.mark_sharded(buf, {0: "dp"})
            vals = _data("vals", (R, W), dtype="int64")
            start = _data("start", (R,), dtype="int64")
            count = _data("count", (R,), dtype="int64")
            layers.span_scatter(buf, vals, start, count)
        facts = self._facts(main)
        assert facts.spec("@rulebuf") == ShardSpec.of({0: "dp"})

        import jax.numpy as jnp

        def fn(buf, vals, start, count):
            pos = jnp.arange(T)[None, :]
            rel = pos - start[:, None]
            sel = (rel >= 0) & (rel < count[:, None]) & (rel < W)
            relc = jnp.clip(rel, 0, W - 1)
            va = jnp.take_along_axis(vals, relc, axis=1)
            return jnp.where(sel, va.astype(buf.dtype), buf)

        got = _jax_out_pspec(
            fn,
            [np.zeros((R, T), np.int64), np.ones((R, W), np.int64),
             np.zeros(R, np.int64), np.full(R, 2, np.int64)],
            [("dp", None), (), (), ()], 2)
        assert got == _spec_to_pspec(facts.spec("@rulebuf"), 2)

    def test_filtered_softmax_keeps_vocab_shard_and_implies_psum(self):
        R, V = 8, 64
        main, startup, g = _guarded()
        with g:
            z = _data("z", (R, V), {1: "tp"})
            p = layers.filtered_softmax(z, temperature=0.8, top_k=8,
                                        top_p=0.95)
        facts = self._facts(main)
        assert facts.spec(p.name) == ShardSpec.of({1: "tp"})
        psums = [es for es in facts.collective_events
                 if es.event.kind == "psum"]
        assert psums and all("tp" in es.event.axes for es in psums)

        import jax
        import jax.numpy as jnp

        def fn(z):
            zz = (z / 0.8).astype(jnp.float32)
            kth = jax.lax.top_k(zz, 8)[0][..., -1:]
            zz = jnp.where(zz >= kth, zz, -jnp.inf)
            pr = jax.nn.softmax(zz, axis=-1)
            ps = jnp.sort(pr, axis=-1)[..., ::-1]
            cs = jnp.cumsum(ps, axis=-1)
            keep = (cs - ps) < 0.95
            cut = jnp.min(jnp.where(keep, ps, jnp.inf), axis=-1,
                          keepdims=True)
            pr = jnp.where(pr >= cut, pr, 0.0)
            return pr / jnp.sum(pr, axis=-1, keepdims=True)

        got = _jax_out_pspec(fn, [np.random.rand(R, V).astype(
            np.float32)], [(None, "tp")], 2)
        assert got == _spec_to_pspec(facts.spec(p.name), 2)

    def test_sample_categorical_replicates_and_implies_gather(self):
        R, V = 8, 64
        main, startup, g = _guarded()
        with g:
            probs = _data("probs", (R, V), {1: "tp"})
            seed = _data("seed", (R,), dtype="int64")
            pos = _data("pos", (R,), dtype="int64")
            tok = layers.sample_categorical(probs, seed, pos)
        facts = self._facts(main)
        assert facts.spec(tok.name).is_replicated
        ag = [es for es in facts.collective_events
              if es.event.kind == "allgather"]
        assert ag and "tp" in ag[0].event.axes

    def test_spec_accept_replicates_and_implies_gather(self):
        R, V, k = 8, 64, 2
        main, startup, g = _guarded()
        with g:
            props = _data("props", (R, k), dtype="int64")
            dprobs = _data("dprobs", (R, k, V))
            tprobs = _data("tprobs", (R, k + 1, V), {2: "tp"})
            seed = _data("seed", (R,), dtype="int64")
            pos = _data("pos", (R,), dtype="int64")
            adv, toks, acc, fin = layers.spec_accept(
                props, dprobs, tprobs, seed, pos, k=k, end_id=1,
                max_len=16, greedy=True)
        facts = self._facts(main)
        for v in (adv, toks, acc, fin):
            assert facts.spec(v.name).is_replicated, v.name
        ag = [es for es in facts.collective_events
              if es.event.kind == "allgather"]
        assert ag and "tp" in ag[0].event.axes

        import jax.numpy as jnp

        def fn(props, dprobs, tprobs):
            px = jnp.take_along_axis(tprobs[:, :k], props[..., None],
                                     axis=-1)[..., 0]
            qx = jnp.take_along_axis(dprobs, props[..., None],
                                     axis=-1)[..., 0]
            a = jnp.cumprod((qx < px).astype(jnp.int64),
                            axis=1).sum(axis=1)
            return a

        got = _jax_out_pspec(
            fn,
            [np.zeros((R, k), np.int64),
             np.random.rand(R, k, V).astype(np.float32),
             np.random.rand(R, k + 1, V).astype(np.float32)],
            [(), (), (None, None, "tp")], 1)
        assert got == _spec_to_pspec(facts.spec(adv.name), 1)


# ---------------------------------------------------------------------------
# the tp-sharded decoder fixture (analysis/targets.py zoo target)
# ---------------------------------------------------------------------------
@pytest.fixture(scope="module")
def tp_fixture():
    from paddle_tpu.models import sharded_decoder

    return sharded_decoder.build_tp_sharded_decoder_step()


class TestShardedDecoderFixture:
    def test_strict_green(self, tp_fixture):
        ds = run_checks(tp_fixture.program)
        assert not [d for d in ds
                    if d.severity in (ERROR, WARNING)], \
            [d.format() for d in ds][:5]

    def test_head_sharded_attention_flow(self, tp_fixture):
        # the propagated layout is the Megatron one: KV pinned on
        # heads, row-parallel projections implying the psums
        facts = absint.analyze(tp_fixture.program)
        assert facts.converged
        for name in tp_fixture.kv_names:
            assert facts.spec(name) == ShardSpec.of({1: "tp"}), name
        psums = [es for es in facts.collective_events
                 if es.event.kind == "psum"]
        # row-parallel self_out/cross_out/fc2 per layer
        assert len(psums) >= 3 * 2
        assert all(es.event.axes == ("tp",) for es in psums)

    def test_sharding_facts_are_stable_surface_only(self, tp_fixture):
        facts = absint.analyze(tp_fixture.program)
        stable = facts.stable_sharding_facts()
        # the REAL lowering's mesh: tp only (dp replica lanes are
        # separate server instances on disjoint device slices, not a
        # mesh axis of one program)
        assert stable["@mesh"] == "tp=2"
        assert stable["logits.w"] == "dim1:tp"
        # tmp_N propagation intermediates stay OUT of the baseline
        assert not any(k.startswith("tmp") or ".tmp" in k
                       for k in stable)


# ---------------------------------------------------------------------------
# baseline drift gate for sharding_facts
# ---------------------------------------------------------------------------
class TestShardingFactsBaseline:
    def _report(self, target, sharding):
        from paddle_tpu.analysis.baseline import TargetReport

        rep = TargetReport(target)
        rep.sharding = dict(sharding)
        return rep

    def test_changed_fact_fails_until_refresh(self):
        from paddle_tpu.analysis.baseline import (baseline_payload,
                                                  diff_against_baseline)

        base = baseline_payload(
            [self._report("t:step", {"w": "dim1:tp"})])
        drifted = [self._report("t:step", {"w": "dim0:tp"})]
        new, _res = diff_against_baseline(drifted, base)
        assert new == ["t:step|w=dim0:tp (was dim1:tp: sharding "
                       "drift)"]
        refreshed = baseline_payload(drifted)
        assert diff_against_baseline(drifted, refreshed) == ([], [])

    def test_new_and_gone_facts(self):
        from paddle_tpu.analysis.baseline import (baseline_payload,
                                                  diff_against_baseline)

        base = baseline_payload(
            [self._report("t:step", {"w": "dim1:tp"})])
        now = [self._report("t:step", {"v": "dim0:dp"})]
        new, resolved = diff_against_baseline(now, base)
        assert new == ["t:step|v=dim0:dp (new sharding fact)"]
        assert resolved == ["t:step|w (sharding fact gone)"]
