"""Streaming front door (ISSUE 20): per-token delivery, cancellation
that frees device state, and deadline-aware overload control.

The contracts this module pins:

* **byte parity** — the streamed token sequence is byte-identical to
  the generated region of the whole-response row on EVERY decode
  front (dense, paged+radix sessions, speculative n-gram), with
  monotone 0-based sequence numbers and the finish marker agreeing
  with the row's terminator; streaming adds NO fetches and NO
  programs (zero steady-state compiles is unchanged);
* **cancellation frees device state** — 100 requests cancelled
  mid-decode across the three fronts release every lane, block,
  prompt entry and radix hold (pool gauges return to baseline), the
  replies fail with the typed ``RequestCancelled``, and the server
  keeps serving; a cancelled session's pins release on close_session;
* **deadlines** — ``submit(deadline_ms=)`` tears down queued AND live
  requests with the typed, non-retryable ``DeadlineExceeded``; the
  Router sheds pre-slot with ``DeadlineUnmeetable`` when the
  costmodel-backed completion estimate cannot meet the SLO, and
  propagates the live remainder into the server's own teardown;
* **taxonomy** — every availability error is a ``ServingUnavailable``
  carrying ``retryable`` + ``retry_after_ms``; retry decisions
  dispatch on TYPE, never on message text;
* **forensics** — cancelled / deadline-missed requests are retained
  as flight-recorder incidents with the reason annotated.
"""
import threading
import time

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import observability as obs
from paddle_tpu.flags import FLAGS
from paddle_tpu.inference import (ContinuousGenerationServer,
                                  PagedContinuousGenerationServer,
                                  apply_eos_sentinel,
                                  count_generated_tokens)
from paddle_tpu.inference.runtime import (AdmissionError,
                                          DeadlineUnmeetable,
                                          ModelRegistry, Router, zoo)
from paddle_tpu.inference.serving import (DeadlineExceeded,
                                          GenerationReply,
                                          RequestCancelled,
                                          ServerClosed, ServerQuiesced,
                                          StreamingReply)
from paddle_tpu.models.decode_engine import (BlockPoolExhausted,
                                             CacheConfig, DraftConfig,
                                             ServingUnavailable)

V, D, H, L, S, MAXT = 16, 32, 2, 1, 10, 32
BS, NB, E = 8, 24, 3
END_ID = 1
N_SLOTS = 4

# the memorizable planted-EOS pool (test_adaptive_spec discipline):
# terminator at varied positions gives model-driven mixed-length
# generations; the p=10 rows never plant one, so their decodes run
# long — the mid-decode window the cancel/deadline tests need
_POOL_RNG = np.random.RandomState(5)
PROMPT_POOL = []
for _p in (1, 2, 3, 4, 6, 8, 10, 10):
    _src = _POOL_RNG.randint(3, V, (S,)).astype(np.int64)
    if _p < S:
        _src[_p:] = END_ID
    PROMPT_POOL.append(_src)
PROMPT_POOL = np.stack(PROMPT_POOL)


def _mixed_len_prompts(rng, n):
    return PROMPT_POOL[rng.randint(0, len(PROMPT_POOL), n)]


@pytest.fixture(scope="module")
def trained():
    """Train the tiny terminator-copy transformer once; build the
    whole-loop oracle plus one bundle per decode front (dense, paged,
    n-gram speculative — the model-free draft keeps the spec front
    inside the fast lane: no draft model to train)."""
    from paddle_tpu import unique_name
    from paddle_tpu.core.scope import Scope
    from paddle_tpu.models import transformer as T

    fluid.seed(0)
    scope = Scope()
    exe = fluid.Executor(fluid.TPUPlace(0))
    with unique_name.guard():
        main, startup, loss = T.build_program(
            seq_len=S, d_model=D, n_heads=H, n_layers=L, d_inner=64,
            vocab=V, with_optimizer=False, dropout_rate=0.0)
        with fluid.program_guard(main, startup):
            fluid.optimizer.Adam(learning_rate=0.02).minimize(loss)
    exe.run(startup, scope=scope)
    rng = np.random.RandomState(7)
    for _ in range(150):
        src = _mixed_len_prompts(rng, 8)
        tgt_in = np.concatenate(
            [np.full((8, 1), 2, np.int64), src[:, :-1]], 1)
        exe.run(main, feed={"src_ids": src, "tgt_ids": tgt_in,
                            "label": src}, fetch_list=[loss],
                scope=scope)
    kwargs = dict(seq_len=S, max_out_len=MAXT, d_model=D, n_heads=H,
                  n_layers=L, d_inner=64, vocab=V, start_id=2,
                  end_id=END_ID)
    with unique_name.guard():
        inc_m, _, _, inc_buf = T.build_incremental_decode_program(
            **kwargs)
    buckets = [N_SLOTS]  # one admission bucket: minimal compile set
    with unique_name.guard():
        dense = T.build_decode_step_program(
            n_slots=N_SLOTS, state_prefix="@fd/",
            admit_buckets=buckets, **kwargs)
    with unique_name.guard():
        paged = T.build_decode_step_program(
            n_slots=N_SLOTS, state_prefix="@fp/",
            cache=CacheConfig(layout="paged", block_size=BS,
                              n_blocks=NB, n_prompt_entries=E),
            **kwargs)
    with unique_name.guard():
        ngram = T.build_decode_step_program(
            n_slots=N_SLOTS, state_prefix="@fn/",
            admit_buckets=buckets,
            draft=DraftConfig(k=2, kind="ngram", ngram=2,
                              k_options=(0, 2)),
            **kwargs)

    def oracle(srcs):
        ref, = exe.run(inc_m, feed={"src_ids": np.asarray(srcs)},
                       fetch_list=[inc_buf], scope=scope)
        return apply_eos_sentinel(np.asarray(ref), end_id=END_ID)

    # pick prompts BY DECODE (the test_radix_reuse discipline):
    # cancel/deadline tests need a LONG generation (several bursts of
    # headroom after the first token); the session tests need one
    # that crosses a block boundary yet leaves extension room in the
    # decode buffer AND terminates (the retained history must end)
    cands = np.concatenate(
        [PROMPT_POOL,
         rng.randint(3, V, (24, S)).astype(np.int64)])
    rows = oracle(cands)
    lens = count_generated_tokens(rows, END_ID)
    long_idx = [i for i in range(len(cands)) if lens[i] >= 12]
    sess_idx = [i for i in range(len(cands))
                if BS + 2 <= lens[i] <= MAXT - 8
                and rows[i][lens[i]] == END_ID]
    assert long_idx, f"no long-decode candidate: {lens}"
    assert sess_idx, f"no session candidate: {lens}"
    return {"exe": exe, "scope": scope, "dense": dense,
            "paged": paged, "ngram": ngram, "oracle": oracle,
            "rng": rng, "long": cands[long_idx],
            "session": cands[sess_idx[0]]}


def _dense(tr, **kw):
    return ContinuousGenerationServer(
        tr["dense"], executor=tr["exe"], scope=tr["scope"], **kw)


def _paged(tr, **kw):
    return PagedContinuousGenerationServer(
        tr["paged"], executor=tr["exe"], scope=tr["scope"], **kw)


def _ngram(tr, **kw):
    return ContinuousGenerationServer(
        tr["ngram"], executor=tr["exe"], scope=tr["scope"], **kw)


def _drain_stream(reply):
    """Iterate a StreamingReply to exhaustion; (seqs, tokens)."""
    seqs, toks = [], []
    for seq, tok in reply:
        seqs.append(seq)
        toks.append(tok)
    return seqs, np.asarray(toks, np.int64)


def _assert_parity(reply, seqs, toks, row):
    """The byte-parity contract: streamed concat == generated region
    row[1:1+n] of the sentinel-normalized whole-response row; seq
    numbers monotone from 0; finish marker agrees with the row."""
    row = np.asarray(row)
    n = int(count_generated_tokens(row[None], END_ID)[0])
    assert seqs == list(range(len(seqs)))
    assert toks.shape == (n,), (toks.shape, n)
    assert np.array_equal(toks, row[1:1 + n]), (toks, row)
    want_fin = "eos" if row[n] == END_ID else "length"
    assert reply.finish_reason == want_fin, (
        reply.finish_reason, want_fin, row)


# --------------------------------------------------------------------
# per-token streaming: byte parity on every decode front
# --------------------------------------------------------------------
class TestStreamingParity:
    def test_dense_stream_byte_parity(self, trained):
        rng = np.random.RandomState(11)
        prompts = _mixed_len_prompts(rng, 6)
        want = trained["oracle"](prompts)
        with _dense(trained, steps_per_tick=4) as srv:
            replies = [srv.submit(p, stream=True) for p in prompts]
            for reply, p, w in zip(replies, prompts, want):
                seqs, toks = _drain_stream(reply)
                row = np.asarray(reply.result(timeout=120))
                # the whole-response row is the oracle row; the
                # stream is its generated region
                assert np.array_equal(row, w), (row, w)
                _assert_parity(reply, seqs, toks, row)
                assert reply.ttft_s is not None \
                    and reply.ttft_s >= 0.0
                assert reply.done()

    def test_dense_stream_cb_form(self, trained):
        rng = np.random.RandomState(12)
        prompt = _mixed_len_prompts(rng, 1)[0]
        got = []
        done = threading.Event()

        def cb(chunk, first_seq, fin):
            got.append((np.asarray(chunk).copy(), first_seq, fin))
            if fin is not None:
                done.set()

        with _dense(trained, steps_per_tick=4) as srv:
            fut = srv.submit(prompt, stream_cb=cb)
            assert isinstance(fut, GenerationReply)
            row = np.asarray(fut.result(timeout=120))
        assert done.wait(timeout=30)
        # final call: empty chunk + finish reason; earlier calls
        # carry data chunks whose first_seq tile contiguously
        *chunks, (tail, tail_seq, fin) = got
        assert tail.size == 0
        n = int(count_generated_tokens(row[None], END_ID)[0])
        assert fin == ("eos" if row[n] == END_ID else "length")
        seq = 0
        toks = []
        for chunk, first_seq, cfin in chunks:
            assert cfin is None and first_seq == seq
            seq += len(chunk)
            toks.extend(int(t) for t in chunk)
        assert tail_seq == n
        assert np.array_equal(np.asarray(toks, np.int64),
                              row[1:1 + n])

    def test_paged_and_radix_session_stream_parity(self, trained):
        rng = np.random.RandomState(13)
        prompts = _mixed_len_prompts(rng, 4)
        with _paged(trained, steps_per_tick=4) as srv:
            # plain paged front
            for p in prompts:
                reply = srv.submit(p, stream=True)
                seqs, toks = _drain_stream(reply)
                _assert_parity(reply, seqs, toks,
                               reply.result(timeout=120))
            # radix session front: turn 1 streams the cold decode,
            # the resubmit admits through the radix tier and must
            # stream the SAME resumed-generation region its own
            # whole-response row reports
            p1 = trained["session"]
            r1 = srv.submit(p1, session_id="chat", stream=True)
            seqs, toks = _drain_stream(r1)
            _assert_parity(r1, seqs, toks, r1.result(timeout=120))
            r2 = srv.submit(p1, session_id="chat",
                            extend_tokens=[5, 6, 7], stream=True)
            seqs2, toks2 = _drain_stream(r2)
            _assert_parity(r2, seqs2, toks2, r2.result(timeout=120))
            assert srv._radix.hit_blocks > 0  # turn 2 really reused
            srv.close_session("chat")

    def test_ngram_spec_stream_parity(self, trained):
        """Speculative front: bursts deliver the accepted runs of
        their ticks; concatenated they must equal the oracle row's
        generated region exactly (the acceptance rule is lossless)."""
        rng = np.random.RandomState(14)
        prompts = _mixed_len_prompts(rng, 4)
        want = trained["oracle"](prompts)
        with _ngram(trained, steps_per_tick=4) as srv:
            for p, w in zip(prompts, want):
                reply = srv.submit(p, stream=True)
                seqs, toks = _drain_stream(reply)
                row = np.asarray(reply.result(timeout=120))
                assert np.array_equal(row, w)
                _assert_parity(reply, seqs, toks, row)

    def test_zero_steady_state_compiles_with_streaming(self, trained):
        rng = np.random.RandomState(15)
        with _dense(trained, steps_per_tick=4) as srv:
            srv.submit(_mixed_len_prompts(rng, 1)[0]).result(120)
            cc = trained["exe"].compile_count
            replies = [srv.submit(p, stream=True)
                       for p in _mixed_len_prompts(rng, 6)]
            for r in replies:
                _drain_stream(r)
                r.result(timeout=120)
            assert trained["exe"].compile_count == cc, (
                "streaming must ride the existing per-burst host "
                "readback — no new programs")


# --------------------------------------------------------------------
# cancellation that frees device state
# --------------------------------------------------------------------
def _cancel_mid_decode(srv, prompt, want: int, budget: int):
    """Stream requests and cancel each after its first token lands
    (the lane is provably live); count cancels until `want` landed.
    A cancel can lose the race with retirement (the request simply
    completes) — those attempts don't count, hence `budget`."""
    landed = 0
    for _ in range(budget):
        if landed == want:
            break
        reply = srv.submit(prompt, stream=True)
        next(iter(reply))              # first burst: lane is live
        if reply.cancel():
            with pytest.raises(RequestCancelled):
                reply.result(timeout=60)
            seqs, _toks = _drain_stream(reply)  # ends, never hangs
            assert reply.finish_reason == "cancelled"
            landed += 1
        else:                          # raced retirement: completed
            reply.result(timeout=60)
    return landed


class TestCancellation:
    def test_hundred_mid_decode_cancels_release_everything(
            self, trained):
        """The ISSUE 20 leak gauntlet: 100 requests cancelled
        mid-decode across dense / paged / radix-session / n-gram-spec
        fronts; every gauge returns to baseline and each server keeps
        serving correct rows afterwards."""
        p_long = trained["long"][0]
        total = 0

        # dense: 34
        with _dense(trained, steps_per_tick=1, drain_steps=1) as srv:
            n = _cancel_mid_decode(srv, p_long, want=34, budget=60)
            assert n == 34
            assert srv.stats()["cancelled"] >= 34
            srv.drain(timeout=60)
            assert all(l is None for l in srv._lanes)
            after = np.asarray(srv.submit(p_long).result(120))
            assert np.array_equal(after, trained["oracle"](
                p_long[None])[0])
            total += n

        # n-gram speculative: 33
        with _ngram(trained, steps_per_tick=1, drain_steps=1) as srv:
            n = _cancel_mid_decode(srv, p_long, want=33, budget=60)
            assert n == 33
            assert srv.stats()["cancelled"] >= 33
            srv.drain(timeout=60)
            assert all(l is None for l in srv._lanes)
            total += n

        # paged + radix sessions: 25 plain + 8 session turn-2 = 33
        with _paged(trained, steps_per_tick=1, drain_steps=1) as srv:
            n = _cancel_mid_decode(srv, p_long, want=25, budget=60)
            assert n == 25
            srv.drain(timeout=60)
            # cancelled lanes adopt NOTHING into the radix tree, but
            # an attempt that raced retirement completed — and plain
            # greedy retirements do adopt their full blocks; evicting
            # the tree must drain the pool to fully free
            held = srv._blocks.in_use
            assert srv._prefix.in_use == 0
            assert srv._radix.evict(NB) == held
            assert srv._blocks.free_count == NB
            p_sess = trained["session"]
            for i in range(8):
                sid = f"gauntlet-{i}"
                srv.submit(p_sess, session_id=sid).result(120)
                r2 = srv.submit(p_sess, session_id=sid,
                                extend_tokens=[3], stream=True)
                next(iter(r2))
                if r2.cancel():
                    with pytest.raises(RequestCancelled):
                        r2.result(timeout=60)
                    n += 1
                else:
                    r2.result(timeout=60)  # raced retirement
                srv.close_session(sid)
            assert n >= 25 + 6, n  # the race may eat a couple
            srv.drain(timeout=60)
            assert srv.stats()["cancelled"] >= n
            # sessions closed: only the radix tree may retain blocks;
            # evicting it drains the pool to fully free
            held = srv._blocks.in_use
            assert srv._prefix.in_use == 0
            assert srv._radix.evict(NB) == held
            assert srv._blocks.free_count == NB
            total += n

        assert total >= 100, total

    def test_cancel_after_done_is_false(self, trained):
        rng = np.random.RandomState(16)
        p = _mixed_len_prompts(rng, 1)[0]
        with _dense(trained) as srv:
            reply = srv.submit(p)
            row = np.asarray(reply.result(timeout=120))
            assert reply.cancel() is False
            assert np.array_equal(
                np.asarray(reply.result(timeout=1)), row)

    def test_mass_cancel_queued_and_live(self, trained):
        """Submit well past slot capacity, cancel EVERYTHING at
        once: queued requests shed at the planning pass, live lanes
        tear down at the burst boundary — every reply fails typed,
        nothing leaks, the server keeps serving."""
        p = trained["long"][0]
        with _paged(trained, steps_per_tick=1, drain_steps=1) as srv:
            replies = [srv.submit(p) for _ in range(3 * N_SLOTS)]
            for r in replies:
                r.cancel()
            outcomes = {"cancelled": 0, "completed": 0}
            for r in replies:
                try:
                    r.result(timeout=60)
                    outcomes["completed"] += 1
                except RequestCancelled:
                    outcomes["cancelled"] += 1
            # at least the queued tail (everything past one slot
            # generation) must have been cancelled
            assert outcomes["cancelled"] >= 2 * N_SLOTS, outcomes
            srv.drain(timeout=60)
            after = np.asarray(srv.submit(p).result(120))
            assert np.array_equal(
                after, trained["oracle"](p[None])[0])
            srv.drain(timeout=60)
            # only the radix tree (fed by the COMPLETED decodes) may
            # retain blocks; evicting it drains the pool
            held = srv._blocks.in_use
            assert srv._prefix.in_use == 0
            assert srv._radix.evict(NB) == held
            assert srv._blocks.free_count == NB

    def test_cancelled_session_pins_release(self, trained):
        p = trained["session"]
        with _paged(trained, steps_per_tick=1, drain_steps=1) as srv:
            srv.submit(p, session_id="s").result(120)
            r2 = srv.submit(p, session_id="s", extend_tokens=[4],
                            stream=True)
            next(iter(r2))
            cancelled = r2.cancel()
            if cancelled:
                with pytest.raises(RequestCancelled):
                    r2.result(timeout=60)
            else:
                r2.result(timeout=60)
            srv.close_session("s")
            srv.drain(timeout=60)
            held = srv._blocks.in_use
            assert srv._prefix.in_use == 0
            assert srv._radix.evict(NB) == held
            assert srv._blocks.free_count == NB


# --------------------------------------------------------------------
# deadlines
# --------------------------------------------------------------------
class TestDeadline:
    def test_deadline_validation(self, trained):
        with _dense(trained) as srv:
            with pytest.raises(ValueError, match="deadline_ms"):
                srv.submit(PROMPT_POOL[0], deadline_ms=0)

    def test_expired_deadline_tears_down_typed(self, trained):
        """A microscopic deadline expires before (or during) the
        first burst: wherever it lands — queued shed or live
        teardown — the reply fails with the typed, non-retryable
        DeadlineExceeded and the server counts it."""
        with _dense(trained, steps_per_tick=1, drain_steps=1) as srv:
            reply = srv.submit(trained["long"][0], deadline_ms=1e-3)
            with pytest.raises(DeadlineExceeded) as ei:
                reply.result(timeout=60)
            assert isinstance(ei.value, ServingUnavailable)
            assert ei.value.retryable is False
            assert ei.value.retry_after_ms is None
            assert srv.stats()["deadline_expired"] == 1

    def test_live_deadline_streaming_teardown(self, trained):
        """Deadline expiring mid-decode: the streamed prefix stays
        parity-correct (a prefix of the oracle's generated region),
        iteration ends with finish_reason 'deadline', and held state
        releases."""
        p = trained["long"][0]
        want = trained["oracle"](p[None])[0]
        with _paged(trained, steps_per_tick=1, drain_steps=1) as srv:
            reply = srv.submit(p, stream=True, deadline_ms=20.0)
            seqs, toks = _drain_stream(reply)
            if reply.finish_reason == "deadline":
                with pytest.raises(DeadlineExceeded):
                    reply.result(timeout=60)
                assert srv.stats()["deadline_expired"] == 1
            else:       # a fast burst beat the clock: full parity
                _assert_parity(reply, seqs, toks,
                               reply.result(timeout=60))
            # either way the streamed tokens are a prefix of the
            # oracle generated region, and nothing leaked beyond the
            # radix tree a COMPLETED decode legitimately feeds
            assert np.array_equal(toks, want[1:1 + len(toks)])
            srv.drain(timeout=60)
            held = srv._blocks.in_use
            assert srv._prefix.in_use == 0
            assert srv._radix.evict(NB) == held
            assert srv._blocks.free_count == NB

    def test_generous_deadline_completes(self, trained):
        p = PROMPT_POOL[2]
        with _dense(trained) as srv:
            row = np.asarray(
                srv.submit(p, deadline_ms=120e3).result(120))
            assert np.array_equal(
                row, trained["oracle"](p[None])[0])
            assert srv.stats()["deadline_expired"] == 0


# --------------------------------------------------------------------
# the unified retryable-error taxonomy
# --------------------------------------------------------------------
class TestTaxonomy:
    def test_types_and_retry_contracts(self):
        # one base carries the retry decision for EVERY availability
        # error; clients dispatch on type, never on message text
        for cls, retryable, after in (
                (BlockPoolExhausted, True, 50.0),
                (ServerQuiesced, True, 2.0),
                (ServerClosed, True, 2.0),
                (RequestCancelled, False, None),
                (DeadlineExceeded, False, None)):
            assert issubclass(cls, ServingUnavailable), cls
            e = cls("x")
            assert e.retryable is retryable, cls
            assert e.retry_after_ms == after, cls

    def test_admission_error_per_reason(self):
        assert issubclass(AdmissionError, ServingUnavailable)
        e = AdmissionError("rate-limited", "slow down")
        assert e.retryable and e.retry_after_ms == 100.0
        e = AdmissionError("queue-full", "try later")
        assert e.retryable and e.retry_after_ms == 20.0
        e = AdmissionError("unknown-tenant", "who?")
        assert not e.retryable and e.retry_after_ms is None

    def test_deadline_unmeetable_contract(self):
        e = DeadlineUnmeetable("backlog too deep")
        assert isinstance(e, AdmissionError)
        assert e.reason == "deadline-unmeetable"
        assert e.retryable is False
        e = DeadlineUnmeetable("meetable when idle", retryable=True,
                               retry_after_ms=12.0)
        assert e.retryable is True and e.retry_after_ms == 12.0

    def test_closed_server_raises_typed(self, trained):
        srv = _dense(trained)
        srv.close()
        with pytest.raises(ServingUnavailable) as ei:
            srv.submit(PROMPT_POOL[0])
        assert isinstance(ei.value, ServerClosed)
        assert ei.value.retryable is True


# --------------------------------------------------------------------
# flight-recorder forensics
# --------------------------------------------------------------------
class TestFlightRecorder:
    @pytest.fixture(autouse=True)
    def _obs_hermetic(self):
        saved = FLAGS._values["observability"]
        obs.reset()
        yield
        FLAGS._values["observability"] = saved
        obs.reset()

    def test_cancel_and_deadline_retained_as_incidents(self, trained):
        from paddle_tpu.observability import flight

        FLAGS._values["observability"] = "metrics"
        p = trained["long"][0]
        with _dense(trained, steps_per_tick=1, drain_steps=1) as srv:
            reply = srv.submit(p, stream=True)
            next(iter(reply))
            if reply.cancel():
                with pytest.raises(RequestCancelled):
                    reply.result(timeout=60)
            d = srv.submit(p, deadline_ms=1e-3)
            with pytest.raises(DeadlineExceeded):
                d.result(timeout=60)
        report = flight.RECORDER.incident_report()
        reasons = [i.get("reason") for i in report["incidents"]
                   if i.get("status") == "cancelled"]
        assert "deadline" in reasons, report
        assert reasons, "cancelled/deadline requests must be retained"


# --------------------------------------------------------------------
# router: deadline-aware shedding + propagation
# --------------------------------------------------------------------
class TestRouterFrontdoor:
    def test_unmeetable_deadline_sheds_pre_slot(self):
        registry = ModelRegistry()
        router = Router(registry, start=False)
        try:
            server, _ = zoo.make_fc_server(
                "tiny", 64, 128, 8, executor=registry.executor())
            # a pinned estimator makes the shed decision a test INPUT
            # (the calibrated path is pinned on the generation server
            # in test_expected_service_ms_calibrates)
            server.expected_service_ms = \
                lambda n_tokens=None: 500.0
            registry.load(server=server, alias="m", warm=False,
                          max_inflight=1)
            router.add_tenant("t", max_queue=10)
            feed = {"tiny_x": np.zeros((1, 64), np.float32)}
            with pytest.raises(DeadlineUnmeetable) as ei:
                router.submit("t", "m", feed, deadline_ms=100.0)
            # unmeetable even on an idle box: terminal
            assert ei.value.retryable is False
            assert ei.value.retry_after_ms == 500.0
            st = router.stats()
            assert st["tenants"]["t"]["rejected"][
                "deadline-unmeetable"] == 1
            # meetable-when-idle: the backlog term pushes past the
            # deadline but one service time fits -> retryable
            server.expected_service_ms = \
                lambda n_tokens=None: 50.0
            router.submit("t", "m", feed)  # queued (start=False)
            with pytest.raises(DeadlineUnmeetable) as ei:
                router.submit("t", "m", feed, deadline_ms=60.0)
            assert ei.value.retryable is True
            assert ei.value.retry_after_ms == 50.0
            # an uncalibrated estimator must not shed anyone
            server.expected_service_ms = lambda n_tokens=None: None
            router.submit("t", "m", feed, deadline_ms=60.0)
        finally:
            router.close()
            registry.close()

    def test_deadline_propagates_into_server_teardown(self, trained):
        """End-to-end: the router forwards the live remainder as the
        generation server's own deadline_ms; an SLO the decode cannot
        meet fails typed from the SERVER side (its gauge moves)."""
        registry = ModelRegistry()
        router = Router(registry)
        srv = _dense(trained, steps_per_tick=1, drain_steps=1)
        try:
            registry.load(server=srv, alias="gen", warm=False,
                          max_inflight=N_SLOTS)
            # disable the admission estimator: if an earlier test
            # calibrated the costmodel, the router would (correctly)
            # shed the tight submit pre-slot — this test pins the
            # PROPAGATED teardown, so the request must reach a lane
            srv.expected_service_ms = lambda n_tokens=None: None
            router.add_tenant("t", max_queue=16)
            p = trained["long"][0]
            ok = router.submit("t", "gen", p, deadline_ms=120e3)
            assert np.array_equal(
                np.asarray(ok.result(timeout=120)),
                trained["oracle"](p[None])[0])
            # a throttle stall can expire the SLO while still queued
            # at the router (also typed DeadlineExceeded, but
            # router-side); retry until one teardown provably landed
            # inside the server — its own gauge must move
            for _attempt in range(5):
                tight = router.submit("t", "gen", p, deadline_ms=8.0)
                try:
                    tight.result(timeout=60)
                except DeadlineExceeded as e:
                    assert e.retryable is False
                if srv.stats()["deadline_expired"] >= 1:
                    break
            assert srv.stats()["deadline_expired"] >= 1, (
                "the deadline must tear down inside the server, not "
                "just at the router edge")
        finally:
            router.close()
            registry.close()

    def test_expected_service_ms_calibrates(self, trained):
        """The real costmodel path: with metrics on, serve traffic
        calibrates the throughput fit and expected_service_ms turns
        into a positive, token-monotone estimate."""
        saved = FLAGS._values["observability"]
        FLAGS._values["observability"] = "metrics"
        try:
            with _dense(trained, steps_per_tick=4) as srv:
                for p in _mixed_len_prompts(
                        np.random.RandomState(17), 4):
                    srv.submit(p).result(timeout=120)
                est = srv.expected_service_ms()
                assert est is not None and est > 0.0
                # more tokens can never cost less
                assert srv.expected_service_ms(8 * MAXT) >= est
        finally:
            FLAGS._values["observability"] = saved
