"""Test config: run on a virtual 8-device CPU mesh (multi-chip sharding
tests execute without TPU hardware, per the reference's localhost-
subprocess dist-test strategy, test_dist_base.py)."""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# the TPU-tunnel plugin (axon sitecustomize) force-selects its platform
# via jax.config; an explicit config update wins and keeps unit tests on
# the virtual 8-device CPU mesh (single real chip stays free for bench).
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402

# ---------------------------------------------------------------------------
# fast/slow lanes (VERDICT r4 weak #7: the full suite outgrew its
# documented budget). Modules listed here are auto-marked `slow` —
# subprocess/dist sweeps, pipeline schedule parity (whole-step jit per
# config), model-zoo training runs. Fast lane:
#     python -m pytest tests/ -q -m "not slow"     (~<=10 min)
# Full lane:
#     python -m pytest tests/ -q                   (~35 min)
# ---------------------------------------------------------------------------
SLOW_MODULES = {
    "test_async_ctr",            # subprocess pserver training
    "test_dist_multiprocess",    # multi-process collective/pserver
    "test_pipeline_program",     # whole-step jit per pp config
    "test_pipeline_1f1b",        # manual-vjp schedule compiles
    "test_pipeline_fetch",
    "test_moe_transformer",
    "test_pipeline_moe",
    "test_parallel_executor",    # dp x tp mesh compiles
    "test_book_models",          # model-zoo training sweeps
    "test_book_models2",
    "test_slim_framework",       # compression training loops
    "test_quant_slim",
    "test_contrib_suite",
    "test_control_flow_decode",  # beam-search decode loops
    "test_train_demo",
    "test_sharded_checkpoint",
    "test_sharded_serving",      # trained-model tp/dp serving suite
    #                              (tests/test_sharding_plan.py keeps
    #                              the fast-lane sharded smoke)
    "test_recompute",
    "test_dgc_gradmerge",
    "test_structural_sharding",
    "test_ring_attention",
    "test_moe_program",          # ep-vs-dense parity sweeps
    "test_pallas_attention",     # interpret-mode kernel sweeps
    "test_native_executor",      # C++ builds + decode/GM parity
    "test_pipeline_3d",          # 8-dev 3D mesh compiles
    "test_disagg_serving",       # two-plan phase-sharded serving
    "test_chunked_prefill",      # chunk/disagg serve waves
    #                              (tests/test_chunked_contracts.py
    #                              keeps the fast-lane chunk
    #                              coverage)
}


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: multi-minute compile/subprocess tests; "
        "deselect with -m 'not slow' for the fast lane")


def pytest_collection_modifyitems(config, items):
    for item in items:
        mod = item.module.__name__.rsplit(".", 1)[-1]
        if mod in SLOW_MODULES:
            item.add_marker(pytest.mark.slow)


@pytest.fixture(autouse=True)
def _fresh_state():
    """Each test gets fresh default programs/scope/name counters."""
    import paddle_tpu as fluid
    from paddle_tpu import unique_name
    from paddle_tpu.core import program as prog_mod

    prog_mod._main_program = fluid.Program()
    prog_mod._startup_program = fluid.Program()
    fluid._reset_global_scope()
    unique_name.switch()
    np.random.seed(90)
    fluid.seed(90)
    yield


@pytest.fixture(autouse=True)
def _hermetic_compile_cache(tmp_path):
    """Tier-1 must never read or write a shared on-disk compile cache:
    route FLAGS_compile_cache_dir to this test's tmp_path (and restore
    the mode), so a developer's populated .paddle_tpu_cache — or a
    leaked FLAGS_compile_cache=rw env var — cannot leak executables
    into or out of the suite."""
    from paddle_tpu.core import compile_cache as cc
    from paddle_tpu.flags import FLAGS

    saved = {k: FLAGS._values[k]
             for k in ("compile_cache", "compile_cache_dir",
                       "compile_cache_max_entries",
                       "compile_cache_max_bytes")}
    FLAGS._values["compile_cache"] = "off"
    FLAGS._values["compile_cache_dir"] = str(tmp_path / "ptp_cache")
    FLAGS._values["compile_cache_max_entries"] = 0
    FLAGS._values["compile_cache_max_bytes"] = 0
    cc._CACHES.clear()
    yield
    FLAGS._values.update(saved)
    cc._CACHES.clear()
