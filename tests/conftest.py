"""Test config: run on a virtual 8-device CPU mesh (multi-chip sharding
tests execute without TPU hardware, per the reference's localhost-
subprocess dist-test strategy, test_dist_base.py)."""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# the TPU-tunnel plugin (axon sitecustomize) force-selects its platform
# via jax.config; an explicit config update wins and keeps unit tests on
# the virtual 8-device CPU mesh (single real chip stays free for bench).
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _fresh_state():
    """Each test gets fresh default programs/scope/name counters."""
    import paddle_tpu as fluid
    from paddle_tpu import unique_name
    from paddle_tpu.core import program as prog_mod

    prog_mod._main_program = fluid.Program()
    prog_mod._startup_program = fluid.Program()
    fluid._reset_global_scope()
    unique_name.switch()
    np.random.seed(90)
    fluid.seed(90)
    yield
