"""Multi-tenant serving runtime (paddle_tpu/inference/runtime).

Covers the runtime's four contracts:

* **Registry / hot swap** — fingerprint-keyed load, clone-by-
  fingerprint dedupe, and the zero-loss swap: a mid-traffic alias
  flip loses NO accepted request and steady-state traffic after the
  new model's warm compiles NOTHING.
* **Isolation** — PTA100 scope-collision refusal at load, and the
  noisy-neighbor guarantee: a tenant flooding the shared model must
  not starve a small tenant (weighted deficit round-robin bounds the
  small tenant's p99 well under the flood's).
* **Admission** — token-bucket and queue-bound rejections are NAMED
  (AdmissionError.reason), synchronous at submit.
* **Observability** — stats_json() is one parseable snapshot with
  per-tenant latency/TTFT/queue-time, per-model server stats, and
  cache pressure; the shared executable cache stays within the
  N x (buckets + 1) bound.
"""
import threading
import time

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.inference import AnalysisConfig, create_paddle_predictor
from paddle_tpu.inference.runtime import (AdmissionError, ModelRegistry,
                                          Router, ServingRuntime, zoo)
from paddle_tpu.inference.serving import ServerQuiesced


def _runtime_with_zoo(max_batch_size=8, **rt_kwargs):
    """A ServingRuntime serving the three-model runtime zoo, warmed."""
    rt = ServingRuntime(**rt_kwargs)
    scopes = {}
    for prefix, in_dim, hidden, classes in zoo.DEFAULT_ZOO:
        server, scope = zoo.make_fc_server(
            prefix, in_dim, hidden, classes, executor=rt.executor(),
            max_batch_size=max_batch_size, max_wait_ms=1.0)
        rt.load_model(prefix, server)
        scopes[prefix] = scope
    return rt, scopes


def _req(prefix, rng, rows=1):
    dims = {p: d for p, d, _h, _c in zoo.DEFAULT_ZOO}
    return {f"{prefix}_x": rng.randn(rows, dims[prefix]).astype(
        np.float32)}


class TestRegistry:
    def test_load_get_and_fingerprints(self):
        rt, _ = _runtime_with_zoo()
        try:
            handles = rt.registry.aliases()
            assert sorted(handles) == ["base", "large", "tiny"]
            # three distinct programs -> three distinct fingerprints
            fps = {h.fingerprint for h in handles.values()}
            assert len(fps) == 3
            assert rt.registry.get("tiny") is handles["tiny"]
            with pytest.raises(KeyError, match="no model loaded"):
                rt.registry.get("nope")
        finally:
            rt.close()

    def test_scope_collision_refused_pta100(self):
        """Two models colliding on persistable names in ONE scope are
        refused TWICE: the zoo builder refuses BEFORE the colliding
        startup runs (running it is itself the clobber), and the
        registry's load backstop refuses a colliding server built
        elsewhere."""
        rt = ServingRuntime()
        try:
            s1, scope = zoo.make_fc_server(
                "tiny", 64, 128, 8, executor=rt.executor())
            rt.load_model("a", s1)
            # build-time precheck: same prefix + same scope refused
            # pre-startup, so model 'a''s weights stay untouched
            with pytest.raises(RuntimeError, match="PTA100"):
                zoo.make_fc_server("tiny", 64, 32, 8,
                                   executor=rt.executor(), scope=scope)
            rng = np.random.RandomState(0)
            out = rt.registry.get("a").submit(
                _req("tiny", rng)).result(60.0)
            assert out[0].shape == (1, 8)  # scope uncorrupted
            # load-time backstop: a colliding server built WITHOUT
            # the precheck (no startup run) is refused at load
            from paddle_tpu.inference.serving import (InferenceServer,
                                                      ProgramRunner)
            main, _startup, feeds, fetches = zoo.build_fc_program(
                "tiny", 64, 32, 8)
            runner = ProgramRunner(main, feeds, fetches,
                                   executor=rt.executor(), scope=scope)
            s2 = InferenceServer(runner)
            with pytest.raises(RuntimeError, match="PTA100"):
                rt.load_model("b", s2)
            s2.close()
            # distinct scope: same names are fine (isolated)
            s3, _ = zoo.make_fc_server(
                "tiny", 64, 32, 8, executor=rt.executor())
            rt.load_model("b", s3)
        finally:
            rt.close()

    def test_load_predictor_dedupes_by_fingerprint(self, tmp_path):
        x = fluid.layers.data(name="x", shape=[8], dtype="float32")
        h = fluid.layers.fc(input=x, size=16, act="relu")
        out = fluid.layers.fc(input=h, size=4, act="softmax")
        exe = fluid.Executor(fluid.TPUPlace(0))
        exe.run(fluid.default_startup_program())
        fluid.save_inference_model(str(tmp_path), ["x"], [out], exe)
        pred = create_paddle_predictor(AnalysisConfig(str(tmp_path)))

        registry = ModelRegistry()
        try:
            h1 = registry.load_predictor("m", pred, max_batch_size=4)
            assert h1.fingerprint == pred.fingerprint()
            # same fingerprint -> no-op (no swap, same handle)
            h2 = registry.load_predictor("m", pred, max_batch_size=4)
            assert h2 is h1
            assert registry.swap_count == 0
            # force=True -> a real swap even at the same fingerprint
            h3 = registry.load_predictor("m", pred, max_batch_size=4,
                                         force=True)
            assert h3 is not h1
            assert registry.swap_count == 1
            out = h3.submit(
                {"x": np.ones((1, 8), np.float32)}).result(60.0)
            assert out[0].shape == (1, 4)
            # same fingerprint but CHANGED serving config -> a config
            # update, not a silent no-op keeping the old knobs
            h4 = registry.load_predictor("m", pred, max_batch_size=8,
                                         max_inflight=16)
            assert h4 is not h3
            assert registry.swap_count == 2
            assert h4.server.max_batch_size == 8
            assert h4.max_inflight == 16
            # ...and re-asserting that same config dedupes again
            h5 = registry.load_predictor("m", pred, max_batch_size=8,
                                         max_inflight=16)
            assert h5 is h4
            assert registry.swap_count == 2
        finally:
            registry.close()


class TestHotSwap:
    def test_mid_traffic_swap_zero_loss_zero_steady_compiles(self):
        """The acceptance contract: flip the alias under live traffic;
        every accepted request completes (zero loss), and once the new
        server's warmup is done, traffic compiles NOTHING."""
        rt = ServingRuntime()
        try:
            server, _ = zoo.make_fc_server(
                "tiny", 64, 128, 8, executor=rt.executor(),
                max_batch_size=8, max_wait_ms=1.0)
            h1 = rt.load_model("m", server)
            rt.add_tenant("t", max_queue=100000)
            rng = np.random.RandomState(0)
            replies, stop = [], [False]

            def traffic():
                while not stop[0]:
                    replies.append(rt.submit(
                        "t", "m",
                        {"tiny_x": rng.randn(1, 64).astype(
                            np.float32)}))
                    time.sleep(0.0005)

            th = threading.Thread(target=traffic)
            th.start()
            time.sleep(0.2)
            # different hidden width -> a genuinely NEW fingerprint
            server2, _ = zoo.make_fc_server(
                "tiny", 64, 64, 8, executor=rt.executor(),
                max_batch_size=8, max_wait_ms=1.0)
            h2 = rt.load_model("m", server2)   # warm -> flip -> drain
            assert h2.fingerprint != h1.fingerprint
            # post-swap steady state: zero compiles from here on
            compiles_after_warm = h2.executor.compile_count
            time.sleep(0.2)
            stop[0] = True
            th.join()
            outs = [rep.result(60.0) for rep in replies]
            assert len(outs) == len(replies)   # ZERO accepted lost
            assert all(o[0].shape == (1, 8) for o in outs)
            assert h2.executor.compile_count == compiles_after_warm, \
                "steady-state traffic compiled after the swap warmup"
            st = rt.stats()
            assert st["registry"]["swaps"] == 1
            assert st["registry"]["retired"] == 1
            assert st["tenants"]["t"]["failed"] == 0
        finally:
            rt.close()


class TestNoisyNeighborIsolation:
    def test_flood_does_not_starve_small_tenant(self):
        """One tenant floods the shared model with 30x the small
        tenant's traffic. Weighted deficit round-robin must interleave
        them ~1:1, so the small tenant's p99 stays FAR below the
        flood's (whose backlog waits in its own queue). FIFO pass-
        through would put the small tenant's p99 at the flood's."""
        rt = ServingRuntime()
        try:
            server, _ = zoo.make_fc_server(
                "tiny", 64, 128, 8, executor=rt.executor(),
                max_batch_size=8, max_wait_ms=1.0)
            # modest inflight cap so fairness is decided in the
            # router's queues, not the server's FIFO
            rt.load_model("m", server, max_inflight=8)
            rt.add_tenant("noisy", max_queue=100000)
            rt.add_tenant("small", max_queue=1000,
                          target_p99_ms=10000.0)
            rng = np.random.RandomState(1)
            feed = {"tiny_x": rng.randn(1, 64).astype(np.float32)}
            noisy = [rt.submit("noisy", "m", dict(feed))
                     for _ in range(240)]
            small = [rt.submit("small", "m", dict(feed))
                     for _ in range(8)]
            for rep in small + noisy:
                rep.result(120.0)
            st = rt.stats()
            t_small = st["tenants"]["small"]
            t_noisy = st["tenants"]["noisy"]
            assert t_small["completed"] == 8
            assert t_noisy["completed"] == 240
            assert t_small["latency_ms"]["p99"] <= \
                0.5 * t_noisy["latency_ms"]["p99"], (
                    f"small tenant p99 "
                    f"{t_small['latency_ms']['p99']}ms not isolated "
                    f"from flood p99 {t_noisy['latency_ms']['p99']}ms")
        finally:
            rt.close()

    def test_weights_skew_service_share(self):
        """weight=3 vs weight=1 on equal backlogs: the heavy tenant's
        requests finish sooner on average (it earns 3x the deficit
        credit per pass). Both backlogs are enqueued BEFORE the
        dispatch loop starts (Router(start=False)) so the share is a
        property of DRR ordering, not of submission timing vs this
        host's CPU-throttle stalls."""
        registry = ModelRegistry()
        router = Router(registry, start=False)
        try:
            server, _ = zoo.make_fc_server(
                "tiny", 64, 128, 8, executor=registry.executor(),
                max_batch_size=8, max_wait_ms=1.0)
            registry.load(server=server, alias="m", max_inflight=8)
            router.add_tenant("heavy", weight=3.0, max_queue=100000)
            router.add_tenant("light", weight=1.0, max_queue=100000)
            rng = np.random.RandomState(2)
            feed = {"tiny_x": rng.randn(1, 64).astype(np.float32)}
            h = [router.submit("heavy", "m", dict(feed))
                 for _ in range(120)]
            li = [router.submit("light", "m", dict(feed))
                  for _ in range(120)]
            router.start()
            for rep in h + li:
                rep.result(120.0)
            st = router.stats()
            assert st["tenants"]["heavy"]["latency_ms"]["p50"] < \
                st["tenants"]["light"]["latency_ms"]["p50"]
        finally:
            router.close()
            registry.close()

    def test_fractional_weights_make_progress(self):
        """Normalized weights (summing to 1, e.g. 0.7/0.1) must serve
        every tenant: DRR earnings are scaled so the largest-weight
        backlogged tenant earns a whole credit per pass. Before that
        normalization, weight=0.1 capped its deficit at 0.8 credits
        and the tenant's queue starved forever."""
        rt = ServingRuntime()
        try:
            server, _ = zoo.make_fc_server(
                "tiny", 64, 128, 8, executor=rt.executor(),
                max_batch_size=8, max_wait_ms=1.0)
            rt.load_model("m", server, max_inflight=8)
            rt.add_tenant("big", weight=0.7, max_queue=1000)
            rt.add_tenant("small", weight=0.1, max_queue=1000)
            rng = np.random.RandomState(3)
            feed = {"tiny_x": rng.randn(1, 64).astype(np.float32)}
            reps = [rt.submit(t, "m", dict(feed))
                    for _ in range(40) for t in ("big", "small")]
            for rep in reps:
                rep.result(60.0)   # raises TimeoutError on starvation
            st = rt.stats()
            assert st["tenants"]["small"]["completed"] == 40
            assert st["tenants"]["small"]["failed"] == 0
        finally:
            rt.close()

    def test_blocked_heavy_tenant_does_not_pace_idle_model(self):
        """Work conservation: a high-weight tenant head-of-line
        blocked on a saturated model must not set the DRR earning
        scale for everyone else. Before the fix, normalizing earnings
        over ALL backlogged tenants meant weight 0.99 (blocked on
        'slow', max_inflight=1, ~250 ms per request) paced weight
        0.001's requests to an IDLE model at one per ~990 passes of
        1 ms sleeps — ~1 request/second against idle hardware. Now
        blocked tenants neither earn nor key the scale, so the small
        tenant drains at full speed while the flood is still stuck."""
        rt = ServingRuntime()
        try:
            # 'slow': a lone request sits the full max_wait_ms in the
            # batcher, so with max_inflight=1 the flood tenant's head
            # is capacity-blocked ~250 ms per request.
            slow, _ = zoo.make_fc_server(
                "tiny", 64, 128, 8, executor=rt.executor(),
                max_batch_size=8, max_wait_ms=250.0)
            fast, _ = zoo.make_fc_server(
                "base", 128, 256, 16, executor=rt.executor(),
                max_batch_size=8, max_wait_ms=1.0)
            rt.load_model("slow", slow, max_inflight=1)
            rt.load_model("fast", fast)
            rt.add_tenant("flood", weight=0.99, max_queue=1000)
            rt.add_tenant("small", weight=0.001, max_queue=1000)
            rng = np.random.RandomState(7)
            slow_feed = {"tiny_x": rng.randn(1, 64).astype(np.float32)}
            fast_feed = {"base_x": rng.randn(1, 128).astype(np.float32)}
            flood_reps = [rt.submit("flood", "slow", dict(slow_feed))
                          for _ in range(30)]
            t0 = time.monotonic()
            small_reps = [rt.submit("small", "fast", dict(fast_feed))
                          for _ in range(10)]
            for rep in small_reps:
                rep.result(30.0)
            small_wall = time.monotonic() - t0
            st = rt.stats()
            # the flood's 30 x ~250 ms backlog must still be draining
            # when the small tenant finishes — i.e. small was NOT
            # paced on the flood's blocked time (broken pacing took
            # ~1 s/request here, outlasting the whole flood drain)
            assert st["tenants"]["flood"]["completed"] < 30
            assert small_wall < 6.0, (
                f"small tenant took {small_wall:.1f}s against an idle "
                f"model while the flood tenant was head-blocked")
            for rep in flood_reps:
                rep.result(60.0)
        finally:
            rt.close()


class TestAdmission:
    def test_named_rejections(self):
        registry = ModelRegistry()
        # start=False: requests stay queued, so bounds are
        # deterministic (nothing drains mid-assert)
        router = Router(registry, start=False)
        try:
            server, _ = zoo.make_fc_server(
                "tiny", 64, 128, 8, executor=registry.executor())
            registry.load(server=server, alias="m", warm=False)
            # rate tiny so no whole token refills during the test
            # even across a multi-second throttle stall on this host
            router.add_tenant("t", rate=0.001, burst=2.0,
                              max_queue=10)
            with pytest.raises(AdmissionError) as ei:
                router.submit("ghost", "m", {})
            assert ei.value.reason == "unknown-tenant"
            with pytest.raises(AdmissionError) as ei:
                router.submit("t", "ghost-model", {})
            assert ei.value.reason == "unknown-model"
            feed = {"tiny_x": np.zeros((1, 64), np.float32)}
            router.submit("t", "m", feed)
            router.submit("t", "m", feed)
            # burst=2 spent, negligible refill -> rate-limited
            with pytest.raises(AdmissionError) as ei:
                router.submit("t", "m", feed)
            assert ei.value.reason == "rate-limited"
            router.add_tenant("q", max_queue=2)
            router.submit("q", "m", feed)
            router.submit("q", "m", feed)
            with pytest.raises(AdmissionError) as ei:
                router.submit("q", "m", feed)
            assert ei.value.reason == "queue-full"
            st = router.stats()
            assert st["tenants"]["t"]["rejected"]["rate-limited"] == 1
            assert st["tenants"]["q"]["rejected"]["queue-full"] == 1
        finally:
            router.close()
            registry.close()

    def test_config_validation(self):
        """Misconfigurations fail loudly at construction, not as a
        dead dispatch thread or a silently-inert limit."""
        registry = ModelRegistry()
        try:
            # quantum=0 would ZeroDivisionError in the DRR pass
            # (killing the daemon loop: every request hangs)
            with pytest.raises(ValueError, match="quantum"):
                Router(registry, quantum=0.0, start=False)
            router = Router(registry, start=False)
            try:
                with pytest.raises(ValueError, match="weight"):
                    router.add_tenant("t", weight=0)
                with pytest.raises(ValueError, match="rate"):
                    router.add_tenant("t", rate=0)
                with pytest.raises(ValueError, match="burst"):
                    router.add_tenant("t", rate=5.0, burst=0.5)
                # burst without rate: the token bucket is gated on
                # rate, so this would validate yet limit nothing
                with pytest.raises(ValueError, match="burst"):
                    router.add_tenant("t", burst=5.0)
            finally:
                router.close()
        finally:
            registry.close()

    def test_queue_full_rejection_does_not_burn_rate_tokens(self):
        """A client retrying on queue-full must not drain its token
        bucket: the queue bound is checked BEFORE the rate debit, so
        admitted throughput recovers the moment the queue clears."""
        registry = ModelRegistry()
        router = Router(registry, start=False)
        try:
            server, _ = zoo.make_fc_server(
                "tiny", 64, 128, 8, executor=registry.executor())
            registry.load(server=server, alias="m", warm=False)
            router.add_tenant("t", rate=5.0, burst=2.0, max_queue=1)
            feed = {"tiny_x": np.zeros((1, 64), np.float32)}
            router.submit("t", "m", feed)       # 1 token left
            for _ in range(3):
                with pytest.raises(AdmissionError) as ei:
                    router.submit("t", "m", feed)
                assert ei.value.reason == "queue-full"
            # the 3 rejections spent NO tokens (rate ~5/s refills are
            # negligible over this test's microseconds)
            assert router._tenants["t"].tokens >= 1.0
        finally:
            router.close()
            registry.close()

    def test_closed_router_rejects_and_fails_queued(self):
        registry = ModelRegistry()
        router = Router(registry, start=False)
        server, _ = zoo.make_fc_server(
            "tiny", 64, 128, 8, executor=registry.executor())
        registry.load(server=server, alias="m", warm=False)
        router.add_tenant("t")
        feed = {"tiny_x": np.zeros((1, 64), np.float32)}
        rep = router.submit("t", "m", feed)
        router.close()
        with pytest.raises(AdmissionError, match="router-closed"):
            rep.result(5.0)
        with pytest.raises(AdmissionError) as ei:
            router.submit("t", "m", feed)
        assert ei.value.reason == "router-closed"
        registry.close()


class TestStatsSurface:
    def test_stats_json_and_executable_bound(self):
        """One process, three models, Zipf-ish traffic: stats_json()
        parses and carries the acceptance surface (per-tenant TTFT/
        p99, per-model occupancy, cache pressure), and the shared
        executable cache respects the N x (buckets + 1) bound."""
        import json

        rt, _ = _runtime_with_zoo(max_batch_size=8)
        try:
            # post-warm baseline (model warmup AND the startup-program
            # compiles are all behind us here)
            compiles_after_warm = sum(
                h.executor.compile_count
                for h in rt.registry.aliases().values())
            rt.add_tenant("alpha", weight=2.0, target_p99_ms=5000.0,
                          max_queue=10000)
            rt.add_tenant("beta", max_queue=10000)
            rng = np.random.RandomState(3)
            models = [p for p, *_ in zoo.DEFAULT_ZOO]
            # Zipf-ish popularity over the 3 models
            probs = np.array([1 / (r + 1) for r in range(3)])
            probs /= probs.sum()
            replies = []
            for k in range(120):
                prefix = models[rng.choice(3, p=probs)]
                tenant = "alpha" if k % 3 else "beta"
                replies.append(
                    rt.submit(tenant, prefix, _req(prefix, rng)))
            for rep in replies:
                rep.result(120.0)

            st = json.loads(rt.stats_json())
            for tenant in ("alpha", "beta"):
                ts = st["tenants"][tenant]
                assert ts["completed"] > 0
                assert ts["latency_ms"]["p99"] is not None
                assert ts["ttft_ms"]["p99"] is not None
                assert ts["queue_ms"]["p50"] is not None
            assert st["tenants"]["alpha"]["slo_violations"] == 0
            for prefix in models:
                ms = st["models"][prefix]
                assert ms["kind"] == "InferenceServer"
                assert len(ms["fingerprint"]) == 16
                assert ms["completed"] > 0
                assert ms["batch_occupancy"] is not None
                assert ms["uptime_s"] > 0
            cache = st["cache"]["executable"]
            n_models = len(models)
            ladder = len(rt.registry.get("tiny").server.batch_buckets)
            assert cache["size"] <= n_models * (ladder + 1)
            assert cache["evictions"] == 0
            # zero steady-state compiles: nothing compiled after warm
            assert st["cache"]["compile_count"] == \
                compiles_after_warm
        finally:
            rt.close()

    def test_runtime_stats_reset_window(self):
        rt, _ = _runtime_with_zoo()
        try:
            rt.add_tenant("t", max_queue=1000)
            rng = np.random.RandomState(4)
            for _ in range(5):
                rt.infer("t", "tiny", _req("tiny", rng), timeout=60.0)
            st = rt.stats(reset=True)
            assert st["tenants"]["t"]["completed"] == 5
            st2 = rt.stats()
            assert st2["tenants"]["t"]["completed"] == 0
            assert st2["models"]["tiny"]["requests"] == 0
            # uptime is monotonic across resets
            assert st2["models"]["tiny"]["uptime_s"] >= \
                st["models"]["tiny"]["uptime_s"]
        finally:
            rt.close()


class TestServerLifecycleForSwap:
    def test_quiesce_drain_semantics(self):
        """The swap building blocks directly: a quiesced server
        rejects with ServerQuiesced (retryable), drains its queue,
        and closes cleanly."""
        registry = ModelRegistry()
        server, _ = zoo.make_fc_server(
            "tiny", 64, 128, 8, executor=registry.executor(),
            max_wait_ms=20.0)
        feed = {"tiny_x": np.zeros((1, 64), np.float32)}
        reps = [server.submit(dict(feed)) for _ in range(5)]
        server.quiesce()
        with pytest.raises(ServerQuiesced):
            server.submit(dict(feed))
        assert server.drain(30.0) is True
        for rep in reps:
            assert rep.result(1.0)[0].shape == (1, 8)
        server.close()


class TestRouterCapacityAccounting:
    def test_cancelled_reply_does_not_leak_inflight(self):
        """A caller that times out and cancel()s its reply future
        (never marked running, so cancel succeeds) must not leak the
        model's inflight slot: set_result on the cancelled reply
        raises InvalidStateError inside the done-callback, and the
        decrement must still run or max_inflight wedges the alias
        forever."""
        rng = np.random.RandomState(7)
        rt, _ = _runtime_with_zoo()
        try:
            rt.add_tenant("t", rate=1e9, burst=1000, max_queue=1000)
            # tiny cap so even a couple of leaked slots wedge it
            rt.registry.get("tiny").max_inflight = 2
            cancelled = 0
            for _ in range(300):
                rep = rt.submit("t", "tiny", _req("tiny", rng))
                if rep.cancel():
                    cancelled += 1
                if cancelled >= 3:
                    break
            assert cancelled >= 1, \
                "no submit was cancellable before fulfilment"
            assert rt.drain(timeout=60)
            deadline = time.monotonic() + 10
            while (rt.router.inflight("tiny")
                   and time.monotonic() < deadline):
                time.sleep(0.01)
            assert rt.router.inflight("tiny") == 0
            # capacity intact: a fresh request still completes
            out = rt.infer("t", "tiny", _req("tiny", rng), timeout=30)
            assert np.asarray(out[0]).shape == (1, 8)
        finally:
            rt.close()
