"""Per-op numeric tests (reference test_*_op.py pattern, SURVEY.md §4.1)."""
import numpy as np

from op_test import OpTest


class TestMulOp(OpTest):
    def setUp(self):
        super().setUp()
        self.op_type = "mul"
        x = np.random.random((8, 12)).astype("float32")
        y = np.random.random((12, 7)).astype("float32")
        self.inputs = {"X": x, "Y": y}
        self.attrs = {"x_num_col_dims": 1, "y_num_col_dims": 1}
        self.outputs = {"Out": x @ y}

    def test_output(self):
        self.check_output(atol=1e-4)

    def test_grad(self):
        self.check_grad(["X", "Y"], "Out")


class TestMatmulTranspose(OpTest):
    def setUp(self):
        super().setUp()
        self.op_type = "matmul"
        x = np.random.random((3, 5, 4)).astype("float32")
        y = np.random.random((3, 6, 4)).astype("float32")
        self.inputs = {"X": x, "Y": y}
        self.attrs = {"transpose_X": False, "transpose_Y": True,
                      "alpha": 2.0}
        self.outputs = {"Out": 2.0 * np.einsum("bik,bjk->bij", x, y)}

    def test_output(self):
        self.check_output(atol=1e-4)

    def test_grad(self):
        self.check_grad(["X", "Y"], "Out", max_relative_error=0.01)


class TestElementwiseAddAxisBroadcast(OpTest):
    def setUp(self):
        super().setUp()
        self.op_type = "elementwise_add"
        x = np.random.random((2, 3, 4, 5)).astype("float32")
        y = np.random.random((3,)).astype("float32")
        self.inputs = {"X": x, "Y": y}
        self.attrs = {"axis": 1}
        self.outputs = {"Out": x + y.reshape(1, 3, 1, 1)}

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad(["X", "Y"], "Out")


class TestSoftmaxWithCrossEntropy(OpTest):
    def setUp(self):
        super().setUp()
        self.op_type = "softmax_with_cross_entropy"
        logits = np.random.random((10, 6)).astype("float32")
        label = np.random.randint(0, 6, (10, 1)).astype("int64")
        sm = np.exp(logits - logits.max(-1, keepdims=True))
        sm /= sm.sum(-1, keepdims=True)
        loss = -np.log(sm[np.arange(10), label[:, 0]])[:, None]
        self.inputs = {"Logits": logits, "Label": label}
        self.outputs = {"Loss": loss, "Softmax": sm}

    def test_output(self):
        self.check_output(atol=1e-5)

    def test_grad(self):
        self.check_grad(["Logits"], "Loss", max_relative_error=0.01)


class TestReduceMean(OpTest):
    def setUp(self):
        super().setUp()
        self.op_type = "reduce_mean"
        x = np.random.random((4, 5, 6)).astype("float32")
        self.inputs = {"X": x}
        self.attrs = {"dim": [1], "keep_dim": False,
                      "reduce_all": False}
        self.outputs = {"Out": x.mean(axis=1)}

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad(["X"], "Out")


class TestConv2D(OpTest):
    def setUp(self):
        super().setUp()
        self.op_type = "conv2d"
        x = np.random.random((2, 3, 8, 8)).astype("float32")
        w = np.random.random((4, 3, 3, 3)).astype("float32")
        self.inputs = {"Input": x, "Filter": w}
        self.attrs = {"strides": [1, 1], "paddings": [1, 1],
                      "dilations": [1, 1], "groups": 1}
        import jax

        ref = jax.lax.conv_general_dilated(
            x, w, (1, 1), [(1, 1), (1, 1)],
            dimension_numbers=("NCHW", "OIHW", "NCHW"))
        self.outputs = {"Output": np.asarray(ref)}

    def test_output(self):
        self.check_output(atol=1e-4)


class TestLayerNorm(OpTest):
    def setUp(self):
        super().setUp()
        self.op_type = "layer_norm"
        x = np.random.random((4, 10)).astype("float32")
        scale = np.random.random((10,)).astype("float32")
        bias = np.random.random((10,)).astype("float32")
        mean = x.mean(axis=1, keepdims=True)
        var = x.var(axis=1, keepdims=True)
        y = (x - mean) / np.sqrt(var + 1e-5) * scale + bias
        self.inputs = {"X": x, "Scale": scale, "Bias": bias}
        self.attrs = {"epsilon": 1e-5, "begin_norm_axis": 1}
        self.outputs = {"Y": y, "Mean": mean.reshape(4),
                        "Variance": var.reshape(4)}

    def test_output(self):
        self.check_output(atol=1e-4)

    def test_grad(self):
        self.check_grad(["X", "Scale", "Bias"], "Y",
                        max_relative_error=0.02)


class TestLookupTable(OpTest):
    def setUp(self):
        super().setUp()
        self.op_type = "lookup_table"
        w = np.random.random((17, 8)).astype("float32")
        ids = np.random.randint(0, 17, (5, 1)).astype("int64")
        self.inputs = {"W": w, "Ids": ids}
        self.attrs = {"padding_idx": -1}
        self.outputs = {"Out": w[ids[:, 0]]}

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad(["W"], "Out")


class TestBatchNormTrain(OpTest):
    def setUp(self):
        super().setUp()
        self.op_type = "batch_norm"
        x = np.random.random((4, 3, 5, 5)).astype("float32")
        scale = np.random.random(3).astype("float32")
        bias = np.random.random(3).astype("float32")
        mean_in = np.zeros(3, "float32")
        var_in = np.ones(3, "float32")
        eps, mom = 1e-5, 0.9
        m = x.mean(axis=(0, 2, 3))
        v = x.var(axis=(0, 2, 3))
        y = ((x - m.reshape(1, 3, 1, 1))
             / np.sqrt(v.reshape(1, 3, 1, 1) + eps)
             * scale.reshape(1, 3, 1, 1) + bias.reshape(1, 3, 1, 1))
        self.inputs = {"X": x, "Scale": scale, "Bias": bias,
                       "Mean": mean_in, "Variance": var_in}
        self.attrs = {"epsilon": eps, "momentum": mom, "is_test": False}
        self.outputs = {
            "Y": y,
            "MeanOut": mean_in * mom + m * (1 - mom),
            "VarianceOut": var_in * mom + v * (1 - mom),
            "SavedMean": m,
            "SavedVariance": 1.0 / np.sqrt(v + eps),
        }

    def test_output(self):
        self.check_output(atol=1e-4)


class TestDropoutTestMode(OpTest):
    def setUp(self):
        super().setUp()
        self.op_type = "dropout"
        x = np.random.random((4, 6)).astype("float32")
        self.inputs = {"X": x}
        self.attrs = {"dropout_prob": 0.35, "is_test": True,
                      "dropout_implementation": "downgrade_in_infer"}
        self.outputs = {"Out": x * 0.65, "Mask": np.ones_like(x)}

    def test_output(self):
        self.check_output()


class TestSgdOp(OpTest):
    def setUp(self):
        super().setUp()
        self.op_type = "sgd"
        p = np.random.random((5, 3)).astype("float32")
        g = np.random.random((5, 3)).astype("float32")
        lr = np.array([0.1], dtype="float32")
        self.inputs = {"Param": p, "Grad": g, "LearningRate": lr}
        self.outputs = {"ParamOut": p - 0.1 * g}

    def test_output(self):
        self.check_output()


class TestAdamOp(OpTest):
    def setUp(self):
        super().setUp()
        self.op_type = "adam"
        p = np.random.random((4, 2)).astype("float32")
        g = np.random.random((4, 2)).astype("float32")
        m1 = np.random.random((4, 2)).astype("float32")
        m2 = np.random.random((4, 2)).astype("float32")
        lr = np.array([0.01], "float32")
        b1, b2, eps = 0.9, 0.999, 1e-8
        b1p = np.array([b1 ** 3], "float32")
        b2p = np.array([b2 ** 3], "float32")
        m1o = b1 * m1 + (1 - b1) * g
        m2o = b2 * m2 + (1 - b2) * g * g
        lr_t = 0.01 * np.sqrt(1 - b2p) / (1 - b1p)
        po = p - lr_t * m1o / (np.sqrt(m2o) + eps)
        self.inputs = {"Param": p, "Grad": g, "Moment1": m1,
                       "Moment2": m2, "LearningRate": lr,
                       "Beta1Pow": b1p, "Beta2Pow": b2p}
        self.attrs = {"beta1": b1, "beta2": b2, "epsilon": eps}
        self.outputs = {"ParamOut": po, "Moment1Out": m1o,
                        "Moment2Out": m2o}

    def test_output(self):
        self.check_output(atol=1e-5)


class TestActivations(OpTest):
    def _one(self, op_type, ref, grad=True, x=None):
        self.op_type = op_type
        x = x if x is not None else \
            (np.random.random((4, 7)).astype("float32") + 0.1)
        self.inputs = {"X": x}
        self.attrs = {}
        self.outputs = {"Out": ref(x)}
        self.check_output(atol=1e-5)
        if grad:
            self.check_grad(["X"], "Out", max_relative_error=0.01)

    def test_relu(self):
        x = np.random.uniform(-1, 1, (4, 7)).astype("float32")
        x[np.abs(x) < 0.05] = 0.2  # avoid kink for fd check
        self._one("relu", lambda v: np.maximum(v, 0), x=x)

    def test_sigmoid(self):
        self._one("sigmoid", lambda v: 1 / (1 + np.exp(-v)))

    def test_tanh(self):
        self._one("tanh", np.tanh)

    def test_exp(self):
        self._one("exp", np.exp)

    def test_sqrt(self):
        self._one("sqrt", np.sqrt)

    def test_square(self):
        self._one("square", np.square)


class TestTensorManip(OpTest):
    def test_concat(self):
        self.op_type = "concat"
        a = np.random.random((2, 3)).astype("float32")
        b = np.random.random((2, 5)).astype("float32")
        self.inputs = {"X": [("x0", a), ("x1", b)]}
        self.attrs = {"axis": 1}
        self.outputs = {"Out": np.concatenate([a, b], axis=1)}
        self.check_output()

    def test_split(self):
        self.op_type = "split"
        x = np.random.random((4, 6)).astype("float32")
        self.inputs = {"X": x}
        self.attrs = {"num": 3, "sections": [], "axis": 1}
        parts = np.split(x, 3, axis=1)
        self.outputs = {"Out": [(f"out{i}", p)
                                for i, p in enumerate(parts)]}
        self.check_output()

    def test_transpose(self):
        self.op_type = "transpose"
        x = np.random.random((2, 3, 4)).astype("float32")
        self.inputs = {"X": x}
        self.attrs = {"axis": [2, 0, 1]}
        self.outputs = {"Out": x.transpose(2, 0, 1)}
        self.check_output()

    def test_reshape(self):
        self.op_type = "reshape"
        x = np.random.random((2, 12)).astype("float32")
        self.inputs = {"X": x}
        self.attrs = {"shape": [0, 3, 4]}
        self.outputs = {"Out": x.reshape(2, 3, 4)}
        self.check_output()

    def test_topk(self):
        self.op_type = "top_k"
        x = np.random.random((3, 9)).astype("float32")
        self.inputs = {"X": x}
        self.attrs = {"k": 2}
        idx = np.argsort(-x, axis=1)[:, :2]
        vals = np.take_along_axis(x, idx, axis=1)
        self.outputs = {"Out": vals,
                        "Indices": idx.astype("int32")}
        self.check_output()
