"""OpTests for op-gap batch 2 (numpy/torch oracles).

Parity model: reference tests/unittests/test_bilinear_interp_op.py,
test_nearest_interp_op.py, test_selu_op.py, test_l1_norm_op.py,
test_pad_constant_like.py, test_space_to_depth_op.py,
test_sequence_mask.py, test_sequence_erase_op.py, test_hash_op.py,
test_precision_recall_op.py, test_positive_negative_pair_op.py,
test_proximal_gd_op.py, test_proximal_adagrad_op.py, test_fsp_op.py,
test_split_ids_op.py, test_merge_ids_op.py, test_mine_hard_examples_op.py.
"""
import numpy as np
import pytest

import paddle_tpu as fluid
from op_test import OpTest


class TestBilinearInterp(OpTest):
    def setUp(self):
        super().setUp()
        self.op_type = "bilinear_interp"
        x = np.random.random((2, 3, 4, 4)).astype("float32")
        oh = ow = 8
        # numpy oracle, align_corners=True
        out = np.zeros((2, 3, oh, ow), np.float32)
        for i in range(oh):
            for j in range(ow):
                sy = i * (4 - 1) / (oh - 1)
                sx = j * (4 - 1) / (ow - 1)
                y0, x0 = int(np.floor(sy)), int(np.floor(sx))
                y1, x1 = min(y0 + 1, 3), min(x0 + 1, 3)
                wy, wx = sy - y0, sx - x0
                out[:, :, i, j] = (
                    (1 - wy) * (1 - wx) * x[:, :, y0, x0]
                    + (1 - wy) * wx * x[:, :, y0, x1]
                    + wy * (1 - wx) * x[:, :, y1, x0]
                    + wy * wx * x[:, :, y1, x1])
        self.inputs = {"X": x}
        self.attrs = {"out_h": 8, "out_w": 8, "align_corners": True}
        self.outputs = {"Out": out}

    def test_output(self):
        self.check_output(atol=1e-5)

    def test_grad(self):
        self.check_grad(["X"], "Out")


class TestNearestInterp(OpTest):
    def setUp(self):
        super().setUp()
        self.op_type = "nearest_interp"
        x = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
        # align_corners=False, 2x upsample: each pixel repeats 2x2
        out = x.repeat(2, axis=2).repeat(2, axis=3)
        self.inputs = {"X": x}
        self.attrs = {"out_h": 8, "out_w": 8, "align_corners": False}
        self.outputs = {"Out": out}

    def test_output(self):
        self.check_output()


class TestSelu(OpTest):
    def setUp(self):
        super().setUp()
        self.op_type = "selu"
        x = np.random.uniform(-2, 2, (4, 5)).astype("float32")
        alpha, scale = 1.6732632423543772, 1.0507009873554805
        out = scale * np.where(x > 0, x, alpha * np.exp(x) - alpha)
        self.inputs = {"X": x}
        self.outputs = {"Out": out.astype(np.float32)}

    def test_output(self):
        self.check_output(atol=1e-5)

    def test_grad(self):
        self.check_grad(["X"], "Out")


class TestL1NormMinusPad(OpTest):
    def setUp(self):
        super().setUp()
        self.op_type = "l1_norm"
        x = np.random.uniform(-1, 1, (5, 6)).astype("float32")
        self.inputs = {"X": x}
        self.outputs = {"Out": np.abs(x).sum().reshape(1)}

    def test_output(self):
        self.check_output(atol=1e-5)


class TestMinus(OpTest):
    def setUp(self):
        super().setUp()
        self.op_type = "minus"
        x = np.random.random((3, 4)).astype("float32")
        y = np.random.random((3, 4)).astype("float32")
        self.inputs = {"X": x, "Y": y}
        self.outputs = {"Out": x - y}

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad(["X", "Y"], "Out")


class TestPadConstantLike(OpTest):
    def setUp(self):
        super().setUp()
        self.op_type = "pad_constant_like"
        x = np.zeros((4, 5), np.float32)
        y = np.random.random((2, 3)).astype("float32")
        out = np.full((4, 5), 7.0, np.float32)
        out[:2, :3] = y
        self.inputs = {"X": x, "Y": y}
        self.attrs = {"pad_value": 7.0}
        self.outputs = {"Out": out}

    def test_output(self):
        self.check_output()


class TestSpaceToDepth(OpTest):
    def setUp(self):
        super().setUp()
        self.op_type = "space_to_depth"
        x = np.random.random((1, 2, 4, 4)).astype("float32")
        b = 2
        ref = x.reshape(1, 2, 2, b, 2, b).transpose(0, 3, 5, 1, 2, 4) \
            .reshape(1, 2 * b * b, 2, 2)
        self.inputs = {"X": x}
        self.attrs = {"blocksize": 2}
        self.outputs = {"Out": ref}

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad(["X"], "Out")


class TestFsp(OpTest):
    def setUp(self):
        super().setUp()
        self.op_type = "fsp"
        x = np.random.random((2, 3, 4, 4)).astype("float32")
        y = np.random.random((2, 5, 4, 4)).astype("float32")
        self.inputs = {"X": x, "Y": y}
        self.outputs = {"Out": np.einsum("bihw,bjhw->bij", x, y) / 16}

    def test_output(self):
        self.check_output(atol=1e-5)

    def test_grad(self):
        self.check_grad(["X", "Y"], "Out")


class TestHash:
    def test_deterministic_and_bounded(self):
        prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(prog, startup):
            x = fluid.layers.data(name="x", shape=[4], dtype="int64")
            helper = fluid.layers.nn.LayerHelper("hash", input=x)
            out = prog.global_block.create_var(name="hashed")
            helper.append_op("hash", {"X": x}, {"Out": out},
                             {"num_hash": 2, "mod_by": 1000})
        exe = fluid.Executor(fluid.CPUPlace())
        ids = np.array([[1, 2, 3, 4], [1, 2, 3, 4]], np.int64)
        a, = exe.run(prog, feed={"x": ids}, fetch_list=[out])
        b, = exe.run(prog, feed={"x": ids}, fetch_list=[out])
        np.testing.assert_array_equal(a, b)
        assert a.min() >= 0 and a.max() < 1000
        np.testing.assert_array_equal(a[0], a[1])  # same ids same hash
        assert a.shape == (2, 2, 4)


class TestProximalGD(OpTest):
    def setUp(self):
        super().setUp()
        self.op_type = "proximal_gd"
        p = np.random.random((4, 5)).astype("float32")
        g = np.random.random((4, 5)).astype("float32")
        lr = np.array([0.1], np.float32)
        l1, l2 = 0.02, 0.01
        prox = p - 0.1 * g
        out = np.sign(prox) * np.maximum(np.abs(prox) - 0.1 * l1, 0) \
            / (1 + 0.1 * l2)
        self.inputs = {"Param": p, "Grad": g, "LearningRate": lr}
        self.attrs = {"l1": l1, "l2": l2}
        self.outputs = {"ParamOut": out.astype(np.float32)}

    def test_output(self):
        self.check_output(atol=1e-5)


class TestProximalAdagrad(OpTest):
    def setUp(self):
        super().setUp()
        self.op_type = "proximal_adagrad"
        p = np.random.random((4, 5)).astype("float32")
        g = np.random.random((4, 5)).astype("float32")
        m = np.random.random((4, 5)).astype("float32")
        lr = np.array([0.1], np.float32)
        l1, l2 = 0.02, 0.01
        m_out = m + g * g
        eff = 0.1 / np.sqrt(m_out)
        prox = p - eff * g
        out = np.sign(prox) * np.maximum(np.abs(prox) - eff * l1, 0) \
            / (1 + eff * l2)
        self.inputs = {"Param": p, "Grad": g, "Moment": m,
                       "LearningRate": lr}
        self.attrs = {"l1": l1, "l2": l2}
        self.outputs = {"ParamOut": out.astype(np.float32),
                        "MomentOut": m_out}

    def test_output(self):
        self.check_output(atol=1e-5)


class TestSequenceOpsPadded:
    def _exe(self):
        return fluid.Executor(fluid.CPUPlace())

    def test_sequence_mask(self):
        prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(prog, startup):
            x = fluid.layers.data(name="x", shape=[3], dtype="int64",
                                  append_batch_size=False)
            helper = fluid.layers.nn.LayerHelper("sm", input=x)
            out = prog.global_block.create_var(name="mask")
            helper.append_op("sequence_mask", {"X": x}, {"Y": out},
                             {"maxlen": 5, "out_dtype": "float32"})
        got, = self._exe().run(prog,
                               feed={"x": np.array([3, 0, 5],
                                                   np.int64)},
                               fetch_list=[out])
        ref = np.array([[1, 1, 1, 0, 0], [0] * 5, [1] * 5], np.float32)
        np.testing.assert_array_equal(got, ref)

    def test_sequence_erase(self):
        prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(prog, startup):
            x = fluid.layers.data(name="x", shape=[2, 6],
                                  dtype="int64",
                                  append_batch_size=False)
            sl = fluid.layers.data(name="sl", shape=[2],
                                   dtype="int32",
                                   append_batch_size=False)
            helper = fluid.layers.nn.LayerHelper("se", input=x)
            out = prog.global_block.create_var(name="erased")
            olen = prog.global_block.create_var(name="erased_len")
            helper.append_op("sequence_erase",
                             {"X": x, "SeqLen": sl},
                             {"Out": out, "OutLen": olen},
                             {"tokens": [0, 2]})
        xs = np.array([[1, 0, 2, 3, 0, 9],
                       [2, 2, 1, 4, 5, 6]], np.int64)
        lens = np.array([6, 4], np.int32)
        got, glen = self._exe().run(prog, feed={"x": xs, "sl": lens},
                                    fetch_list=[out, olen])
        np.testing.assert_array_equal(got[0], [1, 3, 9, 0, 0, 0])
        np.testing.assert_array_equal(got[1], [1, 4, 0, 0, 0, 0])
        np.testing.assert_array_equal(glen, [3, 2])

    def test_sequence_expand_as(self):
        prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(prog, startup):
            x = fluid.layers.data(name="x", shape=[2, 3],
                                  dtype="float32",
                                  append_batch_size=False)
            y = fluid.layers.data(name="y", shape=[2, 4, 1],
                                  dtype="float32",
                                  append_batch_size=False)
            sl = fluid.layers.data(name="sl", shape=[2],
                                   dtype="int32",
                                   append_batch_size=False)
            helper = fluid.layers.nn.LayerHelper("sea", input=x)
            out = prog.global_block.create_var(name="expanded")
            helper.append_op("sequence_expand_as",
                             {"X": x, "Y": y, "SeqLen": sl},
                             {"Out": out}, {})
        xs = np.arange(6, dtype=np.float32).reshape(2, 3)
        got, = self._exe().run(
            prog, feed={"x": xs,
                        "y": np.zeros((2, 4, 1), np.float32),
                        "sl": np.array([4, 2], np.int32)},
            fetch_list=[out])
        assert got.shape == (2, 4, 3)
        np.testing.assert_array_equal(got[0, 3], xs[0])
        np.testing.assert_array_equal(got[1, 1], xs[1])
        np.testing.assert_array_equal(got[1, 2], 0)


class TestMetrics:
    def test_precision_recall_perfect(self):
        prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(prog, startup):
            idx = fluid.layers.data(name="idx", shape=[1],
                                    dtype="int32")
            lab = fluid.layers.data(name="lab", shape=[1],
                                    dtype="int32")
            helper = fluid.layers.nn.LayerHelper("pr", input=idx)
            bm = prog.global_block.create_var(name="bm")
            am = prog.global_block.create_var(name="am")
            st = prog.global_block.create_var(name="st")
            helper.append_op("precision_recall",
                             {"Indices": idx, "Labels": lab},
                             {"BatchMetrics": bm, "AccumMetrics": am,
                              "AccumStatesInfo": st},
                             {"class_number": 3})
        exe = fluid.Executor(fluid.CPUPlace())
        ids = np.array([[0], [1], [2], [1]], np.int32)
        got_bm, got_st = exe.run(prog, feed={"idx": ids, "lab": ids},
                                 fetch_list=[bm, st])
        np.testing.assert_allclose(got_bm, np.ones(6), rtol=1e-6)
        assert got_st[:, 0].sum() == 4  # all TP

    def test_positive_negative_pair(self):
        prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(prog, startup):
            s = fluid.layers.data(name="s", shape=[1],
                                  dtype="float32")
            l = fluid.layers.data(name="l", shape=[1],
                                  dtype="float32")
            q = fluid.layers.data(name="q", shape=[1], dtype="int64")
            helper = fluid.layers.nn.LayerHelper("pnp", input=s)
            pos = prog.global_block.create_var(name="pos")
            neg = prog.global_block.create_var(name="neg")
            neu = prog.global_block.create_var(name="neu")
            helper.append_op("positive_negative_pair",
                             {"Score": s, "Label": l, "QueryID": q},
                             {"PositivePair": pos,
                              "NegativePair": neg,
                              "NeutralPair": neu}, {})
        exe = fluid.Executor(fluid.CPUPlace())
        # query 0: scores agree with labels (1 pos pair); query 1:
        # scores disagree (1 neg pair)
        feed = {"s": np.array([[0.9], [0.1], [0.2], [0.7]],
                              np.float32),
                "l": np.array([[1], [0], [1], [0]], np.float32),
                "q": np.array([[0], [0], [1], [1]], np.int64)}
        p, n, u = exe.run(prog, feed=feed, fetch_list=[pos, neg, neu])
        assert float(p.reshape(-1)[0]) == 1.0
        assert float(n.reshape(-1)[0]) == 1.0
        assert float(u.reshape(-1)[0]) == 0.0


class TestSplitMergeIds:
    def test_split_then_merge_roundtrip(self):
        prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(prog, startup):
            ids = fluid.layers.data(name="ids", shape=[6],
                                    dtype="int64",
                                    append_batch_size=False)
            helper = fluid.layers.nn.LayerHelper("si", input=ids)
            s0 = prog.global_block.create_var(name="s0")
            s1 = prog.global_block.create_var(name="s1")
            helper.append_op("split_ids", {"Ids": ids},
                             {"Out": [s0, s1]}, {})
        exe = fluid.Executor(fluid.CPUPlace())
        ids_np = np.array([0, 1, 2, 3, 4, 5], np.int64)
        a, b = exe.run(prog, feed={"ids": ids_np},
                       fetch_list=[s0, s1])
        np.testing.assert_array_equal(a, [0, -1, 1, -1, 2, -1])
        np.testing.assert_array_equal(b, [-1, 0, -1, 1, -1, 2])


class TestMineHardExamples:
    def test_hardest_negatives_selected(self):
        prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(prog, startup):
            cl = fluid.layers.data(name="cl", shape=[1, 6],
                                   dtype="float32",
                                   append_batch_size=False)
            mi = fluid.layers.data(name="mi", shape=[1, 6],
                                   dtype="int32",
                                   append_batch_size=False)
            helper = fluid.layers.nn.LayerHelper("mhe", input=cl)
            neg = prog.global_block.create_var(name="neg")
            upd = prog.global_block.create_var(name="upd")
            helper.append_op("mine_hard_examples",
                             {"ClsLoss": cl, "MatchIndices": mi},
                             {"NegIndices": neg,
                              "UpdatedMatchIndices": upd},
                             {"neg_pos_ratio": 2.0})
        exe = fluid.Executor(fluid.CPUPlace())
        cls_loss = np.array([[0.1, 0.9, 0.3, 0.8, 0.2, 0.5]],
                            np.float32)
        match = np.array([[0, -1, -1, -1, -1, -1]], np.int32)
        got, _ = exe.run(prog, feed={"cl": cls_loss, "mi": match},
                         fetch_list=[neg, upd])
        # 1 positive -> 2 negatives: hardest unmatched are idx 1 (0.9)
        # and idx 3 (0.8)
        picked = set(got[0][got[0] >= 0].tolist())
        assert picked == {1, 3}


class TestModelAverageOp(OpTest):
    def setUp(self):
        super().setUp()
        self.op_type = "average_accumulates"
        p = np.random.random((3, 4)).astype("float32")
        s1 = np.zeros((3, 4), np.float32)
        s2 = np.zeros((3, 4), np.float32)
        s3 = np.zeros((3, 4), np.float32)
        na = np.array([0.0], np.float32)
        ona = np.array([0.0], np.float32)
        nu = np.array([0.0], np.float32)
        self.inputs = {"param": p, "in_sum_1": s1, "in_sum_2": s2,
                       "in_sum_3": s3, "in_num_accumulates": na,
                       "in_old_num_accumulates": ona,
                       "in_num_updates": nu}
        self.attrs = {"average_window": 0.5,
                      "max_average_window": 100,
                      "min_average_window": 10}
        self.outputs = {"out_sum_1": s1 + p, "out_sum_2": s2,
                        "out_sum_3": s3,
                        "out_num_accumulates": np.array([1]),
                        "out_old_num_accumulates": np.array([0]),
                        "out_num_updates": np.array([1])}

    def test_output(self):
        self.check_output(atol=1e-5)


class TestNewIRPasses:
    def _build_manual_attention(self, dropout=False):
        """The reference nets.py scaled_dot_product_attention shape:
        matmul(qk, transpose_Y) -> scale -> softmax -> matmul(v)."""
        prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(prog, startup):
            q = fluid.layers.data(name="q", shape=[4, 6, 8],
                                  dtype="float32")
            k = fluid.layers.data(name="k", shape=[4, 6, 8],
                                  dtype="float32")
            v = fluid.layers.data(name="v", shape=[4, 6, 8],
                                  dtype="float32")
            s = fluid.layers.matmul(q, k, transpose_y=True)
            s = fluid.layers.scale(s, scale=8 ** -0.5)
            w = fluid.layers.softmax(s)
            if dropout:
                w = fluid.layers.dropout(
                    w, 0.1, dropout_implementation="upscale_in_train",
                    is_test=True)
            out = fluid.layers.matmul(w, v)
        return prog, out

    def test_attention_fuse_matches_unfused(self):
        from paddle_tpu import ir

        prog, out = self._build_manual_attention()
        rng = np.random.RandomState(0)
        feed = {n: rng.randn(2, 4, 6, 8).astype(np.float32)
                for n in ("q", "k", "v")}
        exe = fluid.Executor(fluid.CPUPlace())
        ref, = exe.run(prog, feed=feed, fetch_list=[out])
        ir.apply_passes(prog, ["attention_fuse_pass"],
                        protected={out.name})
        types = [op.type for op in prog.global_block.ops]
        assert "attention" in types and "softmax" not in types
        got, = exe.run(prog, feed=feed, fetch_list=[out])
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)

    def test_attention_fuse_keeps_protected_intermediates(self):
        from paddle_tpu import ir

        prog, out = self._build_manual_attention()
        # protect the softmax output -> fusion must NOT fire
        sm_out = [op.output("Out")[0] for op in prog.global_block.ops
                  if op.type == "softmax"][0]
        ir.apply_passes(prog, ["attention_fuse_pass"],
                        protected={out.name, sm_out})
        types = [op.type for op in prog.global_block.ops]
        assert "attention" not in types

    def test_identity_elimination(self):
        from paddle_tpu import ir

        prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(prog, startup):
            x = fluid.layers.data(name="x", shape=[4],
                                  dtype="float32")
            a = fluid.layers.scale(x, scale=1.0, bias=0.0)  # no-op
            b = fluid.layers.cast(a, "float32")             # no-op
            out = fluid.layers.scale(b, scale=2.0)
        n_before = len(prog.global_block.ops)
        ir.apply_passes(prog, ["identity_elimination_pass"],
                        protected={out.name})
        types = [op.type for op in prog.global_block.ops]
        assert len(prog.global_block.ops) < n_before
        assert "cast" not in types
        exe = fluid.Executor(fluid.CPUPlace())
        xs = np.ones((2, 4), np.float32)
        got, = exe.run(prog, feed={"x": xs}, fetch_list=[out])
        np.testing.assert_allclose(got, 2 * xs)

    def test_attention_fuse_dropout_arm(self):
        from paddle_tpu import ir

        # is_test dropout in the chain -> fuses with dropout_rate 0
        prog, out = self._build_manual_attention(dropout=True)
        rng = np.random.RandomState(0)
        feed = {n: rng.randn(2, 4, 6, 8).astype(np.float32)
                for n in ("q", "k", "v")}
        exe = fluid.Executor(fluid.CPUPlace())
        ref, = exe.run(prog, feed=feed, fetch_list=[out])
        ir.apply_passes(prog, ["attention_fuse_pass"],
                        protected={out.name})
        attn = [op for op in prog.global_block.ops
                if op.type == "attention"]
        assert len(attn) == 1
        assert attn[0].attr("dropout_rate") == 0.0  # is_test
        got, = exe.run(prog, feed=feed, fetch_list=[out])
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)

    def test_attention_fuse_rejects_non_last_axis_softmax(self):
        from paddle_tpu import ir

        prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(prog, startup):
            q = fluid.layers.data(name="q", shape=[4, 6, 8],
                                  dtype="float32")
            k = fluid.layers.data(name="k", shape=[4, 6, 8],
                                  dtype="float32")
            v = fluid.layers.data(name="v", shape=[4, 6, 8],
                                  dtype="float32")
            s = fluid.layers.matmul(q, k, transpose_y=True)
            w = fluid.layers.softmax(s, axis=1)
            out = fluid.layers.matmul(w, v)
        ir.apply_passes(prog, ["attention_fuse_pass"],
                        protected={out.name})
        assert "attention" not in [op.type
                                   for op in prog.global_block.ops]

    def test_identity_elim_respects_inplace_rewrites(self):
        from paddle_tpu import ir

        # snap = assign(x); x += 1; out = snap + x  -- the assign must
        # SURVIVE (rewiring snap->x would read the post-increment x)
        prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(prog, startup):
            x = fluid.layers.data(name="x", shape=[1],
                                  dtype="float32",
                                  append_batch_size=False)
            snap = fluid.layers.tensor.assign(x)
            fluid.layers.increment(x, value=1.0)
            out = fluid.layers.elementwise_add(snap, x)
        exe = fluid.Executor(fluid.CPUPlace())
        feed = {"x": np.array([1.0], np.float32)}
        ref, = exe.run(prog, feed=feed, fetch_list=[out])
        ir.apply_passes(prog, ["identity_elimination_pass"],
                        protected={out.name})
        got, = exe.run(prog, feed=feed, fetch_list=[out])
        np.testing.assert_allclose(got, ref)  # 1 + 2 = 3, not 4

    def test_pass_invalidates_executor_cache(self):
        from paddle_tpu import ir

        prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(prog, startup):
            x = fluid.layers.data(name="x", shape=[2],
                                  dtype="float32")
            a = fluid.layers.scale(x, scale=1.0, bias=0.0)
            out = fluid.layers.scale(a, scale=3.0)
        exe = fluid.Executor(fluid.CPUPlace())
        feed = {"x": np.ones((1, 2), np.float32)}
        exe.run(prog, feed=feed, fetch_list=[out])  # warm the cache
        v0 = prog._version
        ir.apply_passes(prog, ["identity_elimination_pass"],
                        protected={out.name})
        assert prog._version != v0  # removal-only pass must bump too
        got, = exe.run(prog, feed=feed, fetch_list=[out])
        np.testing.assert_allclose(got, 3.0)
