"""Pipeline parallelism as a framework capability: Program partition +
GPipe schedule + the program's own optimizer ops (parallel/
pipeline_program.py).

Parity standard (VERDICT r2 #3): a transformer (not a toy) trained
pp=2 on the virtual mesh must produce the same losses as the
single-device Executor to tight tolerance over >=5 steps.
"""
import numpy as np
import pytest

import jax

import paddle_tpu as fluid
from paddle_tpu.parallel.mesh import make_mesh, MeshConfig
from paddle_tpu.parallel.pipeline_program import (
    PipelineTrainer, PipelinePartitionError, propose_loops)


def _fresh():
    fluid._reset_global_scope()
    from paddle_tpu import unique_name
    unique_name.switch()


def _build_mlp(n_layers=4, seed=11):
    prog, startup = fluid.Program(), fluid.Program()
    prog._seed = seed
    with fluid.program_guard(prog, startup):
        x = fluid.layers.data(name="x", shape=[16], dtype="float32")
        y = fluid.layers.data(name="y", shape=[1], dtype="int64")
        h = x
        bounds = [h.name]
        for i in range(n_layers):
            h = fluid.layers.fc(
                h, size=16, act="tanh",
                param_attr=fluid.ParamAttr(name=f"l{i}_w"),
                bias_attr=fluid.ParamAttr(name=f"l{i}_b"))
            bounds.append(h.name)
        logits = fluid.layers.fc(
            h, size=3, param_attr=fluid.ParamAttr(name="head_w"),
            bias_attr=fluid.ParamAttr(name="head_b"))
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, y))
        fluid.optimizer.Adam(0.01).minimize(loss)
    return prog, startup, loss, bounds


def _mlp_data():
    rng = np.random.RandomState(0)
    xs = rng.randn(32, 16).astype(np.float32)
    ys = np.argmax(xs[:, :3], 1).astype(np.int64)[:, None]
    return xs, ys


def _exec_losses(prog, startup, loss, feed, steps):
    exe = fluid.Executor(fluid.CPUPlace())
    sc = fluid.Scope()
    exe.run(startup, scope=sc)
    out = []
    for _ in range(steps):
        l, = exe.run(prog, feed=feed, fetch_list=[loss], scope=sc)
        out.append(float(np.asarray(l).reshape(-1)[0]))
    return out


def _trainer_losses(prog, startup, loss, loops, feed, steps, mesh=None,
                    n_micro=1):
    exe = fluid.Executor(fluid.CPUPlace())
    sc = fluid.Scope()
    exe.run(startup, scope=sc)
    tr = PipelineTrainer(prog, loss, loops=loops, mesh=mesh,
                         n_micro=n_micro)
    tr.initialize(sc)
    out = []
    for _ in range(steps):
        l, = tr.run(feed=feed)
        out.append(float(np.asarray(l).reshape(-1)[0]))
    return out, tr, sc


class TestScanOverLayers:
    """pp=1: the loop lowers to lax.scan over stacked layer params."""

    def test_mlp_parity_with_executor(self):
        xs, ys = _mlp_data()
        prog, startup, loss, bounds = _build_mlp()
        base = _exec_losses(prog, startup, loss,
                            {"x": xs, "y": ys}, 6)
        _fresh()
        prog2, startup2, loss2, bounds2 = _build_mlp()
        got, _, _ = _trainer_losses(prog2, startup2, loss2, [bounds2],
                                    {"x": xs, "y": ys}, 6)
        np.testing.assert_allclose(base, got, rtol=2e-4, atol=2e-5)

    def test_write_back_syncs_scope(self):
        xs, ys = _mlp_data()
        prog, startup, loss, bounds = _build_mlp()
        _, tr, sc = _trainer_losses(prog, startup, loss, [bounds],
                                    {"x": xs, "y": ys}, 3)
        before = np.asarray(sc._get("l0_w")).copy()
        tr.write_back(sc)
        after = np.asarray(sc._get("l0_w"))
        assert np.abs(after - before).max() > 0

    def test_scan_shrinks_the_jaxpr(self):
        """The point of the lowering: program size stops growing
        linearly in depth."""
        xs, ys = _mlp_data()

        def jaxpr_len(n_layers):
            _fresh()
            prog, startup, loss, bounds = _build_mlp(n_layers)
            exe = fluid.Executor(fluid.CPUPlace())
            sc = fluid.Scope()
            exe.run(startup, scope=sc)
            tr = PipelineTrainer(prog, loss, loops=[bounds])
            tr.initialize(sc)
            feeds = {"x": xs, "y": ys}
            step = tr._build_step()
            jx = jax.make_jaxpr(step)(tr.state, feeds, tr._rng)
            return len(str(jx))

        l4, l8 = jaxpr_len(4), jaxpr_len(8)
        # scan keeps ONE copy of the layer body; growth comes only
        # from the per-layer optimizer ops, far below linear doubling
        assert l8 < l4 * 1.5, (l4, l8)


class TestGPipeProgram:
    def test_mlp_pp2_parity(self):
        xs, ys = _mlp_data()
        prog, startup, loss, bounds = _build_mlp()
        base = _exec_losses(prog, startup, loss, {"x": xs, "y": ys}, 6)
        _fresh()
        prog2, startup2, loss2, bounds2 = _build_mlp()
        mesh = make_mesh(MeshConfig(pp=2), devices=jax.devices()[:2])
        got, _, _ = _trainer_losses(prog2, startup2, loss2, [bounds2],
                                    {"x": xs, "y": ys}, 6, mesh=mesh,
                                    n_micro=4)
        np.testing.assert_allclose(base, got, rtol=2e-4, atol=2e-5)

    def test_mlp_pp4_two_segments_per_stage(self):
        xs, ys = _mlp_data()
        prog, startup, loss, bounds = _build_mlp(8)
        base = _exec_losses(prog, startup, loss, {"x": xs, "y": ys}, 5)
        _fresh()
        prog2, startup2, loss2, bounds2 = _build_mlp(8)
        mesh = make_mesh(MeshConfig(pp=4), devices=jax.devices()[:4])
        got, _, _ = _trainer_losses(prog2, startup2, loss2, [bounds2],
                                    {"x": xs, "y": ys}, 5, mesh=mesh,
                                    n_micro=8)
        np.testing.assert_allclose(base, got, rtol=2e-4, atol=2e-5)


class TestTransformerPipeline:
    """The VERDICT bar: a real transformer through the Program path."""

    V, T, D, L = 60, 8, 32, 4

    def _data(self):
        rng = np.random.RandomState(0)
        return {
            "src_ids": rng.randint(1, self.V, (16, self.T)).astype(
                np.int64),
            "tgt_ids": rng.randint(1, self.V, (16, self.T)).astype(
                np.int64),
            "label": rng.randint(1, self.V, (16, self.T)).astype(
                np.int64),
        }

    def _build(self, dropout=0.0, seed=5):
        from paddle_tpu.models import transformer as T

        main, startup, loss = T.build_program(
            seq_len=self.T, d_model=self.D, n_heads=2,
            n_layers=self.L, d_inner=64, vocab=self.V,
            dropout_rate=dropout, learning_rate=1.0, warmup_steps=40)
        main._seed = seed
        return main, startup, loss

    def test_auto_detects_encoder_and_decoder_loops(self):
        main, _, loss = self._build()
        loops = propose_loops(main, loss.name)
        assert len(loops) == 2
        assert all(len(b) - 1 == self.L for b in loops)

    def test_pp2_loss_parity_with_executor(self):
        feed = self._data()
        main, startup, loss = self._build()
        base = _exec_losses(main, startup, loss, feed, 5)
        _fresh()
        main2, startup2, loss2 = self._build()
        loops = propose_loops(main2, loss2.name)
        mesh = make_mesh(MeshConfig(pp=2), devices=jax.devices()[:2])
        got, _, _ = _trainer_losses(main2, startup2, loss2, loops,
                                    feed, 5, mesh=mesh, n_micro=4)
        np.testing.assert_allclose(base, got, rtol=5e-4, atol=5e-5)
        assert got[-1] < got[0]  # and it actually trains

    def test_pp4_loss_parity(self):
        feed = self._data()
        main, startup, loss = self._build()
        base = _exec_losses(main, startup, loss, feed, 4)
        _fresh()
        main2, startup2, loss2 = self._build()
        loops = propose_loops(main2, loss2.name)
        mesh = make_mesh(MeshConfig(pp=4), devices=jax.devices()[:4])
        got, _, _ = _trainer_losses(main2, startup2, loss2, loops,
                                    feed, 4, mesh=mesh, n_micro=4)
        np.testing.assert_allclose(base, got, rtol=5e-4, atol=5e-5)

    def test_pp2_tp2_composed_loss_parity(self):
        """pp composes with tp on one mesh (VERDICT r3 next #4): the
        GPipe ring is manual over 'pp', GSPMD partitions the segment
        matmuls over 'tp' by the structural rules, and losses still
        match the single-device Executor."""
        feed = self._data()
        main, startup, loss = self._build()
        base = _exec_losses(main, startup, loss, feed, 4)
        _fresh()
        main2, startup2, loss2 = self._build()
        loops = propose_loops(main2, loss2.name)
        mesh = make_mesh(MeshConfig(pp=2, tp=2),
                         devices=jax.devices()[:4])
        got, tr, _ = _trainer_losses(main2, startup2, loss2, loops,
                                     feed, 4, mesh=mesh, n_micro=4)
        np.testing.assert_allclose(base, got, rtol=5e-4, atol=5e-5)
        assert got[-1] < got[0]
        # the non-loop params really are tp-sharded (vocab head +
        # embeddings), and a loop param's optimizer state inherited it
        from jax.sharding import PartitionSpec as P
        assert tr.state["logits.w"].sharding.spec == P(None, "tp")
        assert tuple(tr.state["src_word_emb"].sharding.spec)[0] == "tp"
        assert any(
            "tp" in tuple(s for s in tr.state[n].sharding.spec if s)
            for n in tr.state if "_moment1_" in n)

    def test_dropout_trains_through_pipeline(self):
        """No executor parity (rng streams differ), but microbatched
        dropout must train and stay finite."""
        feed = self._data()
        _fresh()
        main, startup, loss = self._build(dropout=0.1)
        loops = propose_loops(main, loss.name)
        mesh = make_mesh(MeshConfig(pp=2), devices=jax.devices()[:2])
        got, _, _ = _trainer_losses(main, startup, loss, loops, feed,
                                    6, mesh=mesh, n_micro=4)
        assert all(np.isfinite(got))
        assert got[-1] < got[0]


class TestCompiledProgramPipeline:
    """PP through the user-facing exe.run(CompiledProgram) API, not a
    side-car trainer object (VERDICT r3 weak #4)."""

    def test_pp2_via_compiled_program(self):
        xs, ys = _mlp_data()
        prog, startup, loss, bounds = _build_mlp()
        base = _exec_losses(prog, startup, loss, {"x": xs, "y": ys}, 5)
        _fresh()
        prog2, startup2, loss2, _ = _build_mlp()
        exe = fluid.Executor(fluid.CPUPlace())
        sc = fluid.Scope()
        exe.run(startup2, scope=sc)
        mesh = make_mesh(MeshConfig(pp=2), devices=jax.devices()[:2])
        cp = fluid.CompiledProgram(prog2).with_data_parallel(
            loss_name=loss2.name, mesh=mesh, n_micro=4)
        got = []
        for _ in range(5):
            l, = exe.run(cp, feed={"x": xs, "y": ys},
                         fetch_list=[loss2], scope=sc)
            got.append(float(np.asarray(l).reshape(-1)[0]))
        np.testing.assert_allclose(base, got, rtol=5e-4, atol=5e-5)
        # scope stays the source of truth: params were written back
        assert np.isfinite(np.asarray(sc._get("l0_w"))).all()

    def test_pp_mesh_requires_loss_name(self):
        prog, startup, loss, bounds = _build_mlp()
        mesh = make_mesh(MeshConfig(pp=2), devices=jax.devices()[:2])
        with pytest.raises(ValueError, match="loss_name"):
            fluid.CompiledProgram(prog).with_data_parallel(mesh=mesh)

    def test_pp_fetch_of_loop_internal_activation_is_named_error(self):
        """Per-example activations inside the stage scan are the one
        thing the schedule truly drops (VERDICT r4 next #5); fetching
        one stays a NAMED error rather than a silent microbatch mean."""
        xs, ys = _mlp_data()
        _fresh()
        prog, startup, loss, bounds = _build_mlp()
        # pre-activation tmp inside layer 1's segment (batch-major,
        # not a boundary var)
        tanh_ops = [op for op in prog.global_block.ops
                    if op.type == "tanh"]
        internal = tanh_ops[1].inputs["X"][0]
        exe = fluid.Executor(fluid.CPUPlace())
        sc = fluid.Scope()
        exe.run(startup, scope=sc)
        mesh = make_mesh(MeshConfig(pp=2), devices=jax.devices()[:2])
        cp = fluid.CompiledProgram(prog).with_data_parallel(
            loss_name=loss.name, mesh=mesh, n_micro=4)
        with pytest.raises(KeyError, match="materialized"):
            exe.run(cp, feed={"x": xs, "y": ys},
                    fetch_list=[loss.name, internal], scope=sc)


class TestPartitionValidation:
    def test_skip_connection_is_a_named_error(self):
        prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(prog, startup):
            x = fluid.layers.data(name="x", shape=[8], dtype="float32")
            y = fluid.layers.data(name="y", shape=[1], dtype="int64")
            h0 = fluid.layers.fc(x, size=8, act="tanh")
            # two isomorphic segments [fc, relu, add]; segment 2's add
            # reads t1, an INTERNAL var of segment 1 (not a boundary)
            t1 = fluid.layers.fc(h0, size=8)
            h1 = fluid.layers.elementwise_add(
                fluid.layers.relu(t1), x)
            t2 = fluid.layers.fc(h1, size=8)
            h2 = fluid.layers.elementwise_add(
                fluid.layers.relu(t2), t1)
            logits = fluid.layers.fc(h2, size=3)
            loss = fluid.layers.mean(
                fluid.layers.softmax_with_cross_entropy(logits, y))
            fluid.optimizer.SGD(0.1).minimize(loss)
        with pytest.raises(PipelinePartitionError,
                           match="skip connection|another segment"):
            PipelineTrainer(prog, loss,
                            loops=[[h0.name, h1.name, h2.name]])

    def test_non_isomorphic_segments_rejected(self):
        prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(prog, startup):
            x = fluid.layers.data(name="x", shape=[8], dtype="float32")
            y = fluid.layers.data(name="y", shape=[1], dtype="int64")
            h0 = fluid.layers.fc(x, size=8, act="tanh")
            h1 = fluid.layers.fc(h0, size=8, act="tanh")
            h2 = fluid.layers.fc(h1, size=8, act="relu")  # extra op mix
            h2 = fluid.layers.elementwise_add(h2, h0)
            logits = fluid.layers.fc(h2, size=3)
            loss = fluid.layers.mean(
                fluid.layers.softmax_with_cross_entropy(logits, y))
            fluid.optimizer.SGD(0.1).minimize(loss)
        with pytest.raises(PipelinePartitionError,
                           match="not isomorphic"):
            PipelineTrainer(prog, loss,
                            loops=[[h0.name, h1.name, h2.name]])

    def test_uneven_segments_rejected(self):
        xs, ys = _mlp_data()
        prog, startup, loss, bounds = _build_mlp(3)
        mesh = make_mesh(MeshConfig(pp=2), devices=jax.devices()[:2])
        with pytest.raises(PipelinePartitionError,
                           match="not divisible"):
            PipelineTrainer(prog, loss, loops=[bounds], mesh=mesh)

    def test_stateful_ops_in_segments_rejected(self):
        """batch_norm's running-stat writes can't be threaded out of
        the stage scan; must be a named error, not silent staleness."""
        prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(prog, startup):
            x = fluid.layers.data(name="x", shape=[8],
                                  dtype="float32")
            y = fluid.layers.data(name="y", shape=[1], dtype="int64")
            h0 = fluid.layers.fc(x, size=8, act="tanh")
            h1 = fluid.layers.batch_norm(fluid.layers.fc(h0, size=8))
            h2 = fluid.layers.batch_norm(fluid.layers.fc(h1, size=8))
            logits = fluid.layers.fc(h2, size=3)
            loss = fluid.layers.mean(
                fluid.layers.softmax_with_cross_entropy(logits, y))
            fluid.optimizer.SGD(0.1).minimize(loss)
        with pytest.raises(PipelinePartitionError,
                           match="persistable|stateful"):
            PipelineTrainer(prog, loss,
                            loops=[[h0.name, h1.name, h2.name]])

    def test_mismatched_broadcast_reads_rejected(self):
        """Each segment reading its OWN pre-loop var would silently
        execute with segment 0's var (segment 0's trace serves all);
        must be a named error."""
        prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(prog, startup):
            x = fluid.layers.data(name="x", shape=[8],
                                  dtype="float32")
            y = fluid.layers.data(name="y", shape=[1], dtype="int64")
            m0 = fluid.layers.scale(x, scale=0.5)   # per-layer biases
            m1 = fluid.layers.scale(x, scale=0.25)
            h0 = fluid.layers.fc(x, size=8, act="tanh")
            h1 = fluid.layers.elementwise_add(
                fluid.layers.fc(h0, size=8), m0)
            h2 = fluid.layers.elementwise_add(
                fluid.layers.fc(h1, size=8), m1)
            logits = fluid.layers.fc(h2, size=3)
            loss = fluid.layers.mean(
                fluid.layers.softmax_with_cross_entropy(logits, y))
            fluid.optimizer.SGD(0.1).minimize(loss)
        with pytest.raises(PipelinePartitionError,
                           match="broadcast|identical"):
            PipelineTrainer(prog, loss,
                            loops=[[h0.name, h1.name, h2.name]])

    def test_run_before_initialize_raises(self):
        prog, startup, loss, bounds = _build_mlp()
        tr = PipelineTrainer(prog, loss, loops=[bounds])
        with pytest.raises(RuntimeError, match="initialize"):
            tr.run(feed={})
