"""Chunked prefill (ISSUE 17 tentpole, Sarathi-Serve-style): the
END-TO-END serve waves on the CPU backend — slow lane; the cheap
contracts (CacheConfig validation, invalidate typestate, preemption
white-box, analysis provers) live in tests/test_chunked_contracts.py
(fast lane):

* the DEVICE parity contract: walking one prompt through the
  ``("chunked", p)`` phase programs (phase-major, every chunk cursor
  per phase, ragged tail zero-padded) writes cross-KV rows
  BIT-IDENTICAL to the monolithic miss admission's encoder — which is
  what lets a chunk-prefilled entry finish as an ordinary prefix HIT;
* the SERVE parity contract: a chunked server and a monolithic server
  produce token-identical results over a mixed miss/hit wave, with
  the chunk-tick arithmetic exact (jobs x n_chunks x phases) and the
  devtel ``tel_chunks`` counter agreeing with the host count;
* the LATENCY contract the chunking exists for: short requests
  admitted while a long cold prompt chunks in complete BEFORE it —
  decode ticks are never blocked behind a whole-prompt prefill;
* zero steady-state compiles: a second traffic wave (including a
  fresh cold prompt -> new chunk job) compiles nothing;
* cross-request radix reuse WITHOUT a session (satellite): an
  identical sessionless resubmit admits through the plain-radix tier
  and re-decodes token-identically;
* disaggregated prefill (unsharded half; the sharded phase-plan half
  lives in test_disagg_serving.py): a DisaggregatedPrefillWorker on
  its OWN scope feeds the decode server through the handoff inbox
  token-identically, and the constructor contracts hold.
"""
import time
import types

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import unique_name
from paddle_tpu.core.scope import Scope
from paddle_tpu.inference.serving import (DisaggregatedPrefillWorker,
                                          PagedContinuousGenerationServer)
from paddle_tpu.models import transformer as T
from paddle_tpu.models.decode_engine import POOL_MARK, CacheConfig

V, D, H, L, S, MAXT = 16, 32, 2, 2, 10, 32
BS, NB, E, C = 8, 24, 3, 4
N_SLOTS = 4
NC = (S + C - 1) // C      # chunk cursors per phase (ragged tail)
NPH = 2 * L + 2            # phases: embed, (kv + attn) per layer, cross
PREFIX = "@cp/"


@pytest.fixture(scope="module")
def built():
    """One untrained transformer + chunked paged bundle for every
    serve test (greedy decode is deterministic either way; training
    buys nothing for parity/scheduling contracts)."""
    fluid.seed(0)
    scope = Scope()
    with unique_name.guard():
        _, t_st, _ = T.build_program(
            seq_len=S, d_model=D, n_heads=H, n_layers=L, d_inner=64,
            vocab=V, with_optimizer=False, dropout_rate=0.0)
    with unique_name.guard():
        bundle = T.build_decode_step_program(
            n_slots=N_SLOTS, admit_buckets=[1, 4], state_prefix=PREFIX,
            seq_len=S, max_out_len=MAXT, d_model=D, n_heads=H,
            n_layers=L, d_inner=64, vocab=V, start_id=2, end_id=1,
            cache=CacheConfig(layout="paged", block_size=BS,
                              n_blocks=NB, n_prompt_entries=E,
                              chunk_tokens=C))
    exe = fluid.Executor(fluid.TPUPlace(0))
    exe.run(t_st, scope=scope)
    rng = np.random.RandomState(7)
    prompts = [rng.randint(3, V, (1, S)).astype(np.int64)
               for _ in range(4)]
    return {"scope": scope, "exe": exe, "bundle": bundle,
            "prompts": prompts, "order": [0, 1, 0, 2, 1, 3, 2, 0]}


def _server(built, **kw):
    kw.setdefault("steps_per_tick", 4)
    return PagedContinuousGenerationServer(
        built["bundle"], executor=built["exe"], scope=built["scope"],
        **kw)


def _wave(srv, built):
    futs = [srv.submit(built["prompts"][i]) for i in built["order"]]
    return [np.asarray(f.result(120.0)) for f in futs]


@pytest.fixture(scope="module")
def mono_ref(built):
    """Monolithic-prefill reference tokens over the standard wave."""
    with _server(built, chunked_prefill=False) as srv:
        toks = _wave(srv, built)
        stats = srv.pool_stats()
    assert stats["chunk_jobs"] == 0 and stats["chunk_ticks"] == 0
    return toks


class TestDeviceChunkParity:
    def test_phase_keys_in_order(self, built):
        b = built["bundle"]
        assert b.chunk_phase_keys == [("chunked", p)
                                      for p in range(NPH)]
        assert b.cache.n_chunks(S) == NC

    def test_phase_walk_bit_exact_vs_monolithic_encoder(self, built):
        """Entry 0: monolithic miss admission. Entry 1: the same
        prompt streamed through every ('chunked', p) phase at every
        chunk cursor (phase-major, ragged last chunk zero-padded).
        The cross-KV rows must match BIT-EXACTLY — that is what lets
        a chunk-prefilled entry later admit as an ordinary HIT."""
        b, exe, scope = built["bundle"], built["exe"], built["scope"]
        b.init_slot_state(scope)
        src = np.random.RandomState(3).randint(
            3, V, (1, S)).astype(np.int64)
        tab = np.zeros((N_SLOTS + 1, MAXT // BS), np.int32)
        tab[0] = np.arange(MAXT // BS)
        scope._set(PREFIX + "block_tab", tab)
        pref = np.full((N_SLOTS + 1,), E, np.int32)
        pref[0] = 0
        scope._set(PREFIX + "prompt_ref", pref)
        exe.run(b.serves[("miss", 1)],
                feed={"src_ids": src,
                      "slots": np.array([0], np.int64),
                      "prompt_slots": np.array([0], np.int64),
                      "n_steps": np.array([0], np.int64),
                      "min_active": np.array([0], np.int64)},
                fetch_list=[b.state["active"]], scope=scope)
        names = [f"{PREFIX}cross_{kind}{li}{POOL_MARK}"
                 for kind in ("k", "v") for li in range(L)]
        want = {n: np.asarray(scope._get(n))[0].copy() for n in names}
        for key in b.chunk_phase_keys:
            for ci in range(NC):
                feed = {"chunk_entry": np.array([1], np.int64),
                        "chunk_pos": np.array([ci * C], np.int64),
                        "n_steps": np.array([0], np.int64),
                        "min_active": np.array([0], np.int64)}
                if key[1] == 0:
                    pad = np.zeros((1, C), np.int64)
                    seg = src[0, ci * C: ci * C + C]
                    pad[0, :len(seg)] = seg
                    feed["chunk_toks"] = pad
                exe.run(b.serves[key], feed=feed,
                        fetch_list=[b.state["active"]], scope=scope)
        for n in names:
            got = np.asarray(scope._get(n))[1]
            np.testing.assert_array_equal(got, want[n], err_msg=n)


class TestServeParity:
    def test_chunked_wave_token_identical(self, built, mono_ref):
        with _server(built) as srv:
            toks = _wave(srv, built)
            stats = srv.pool_stats()
            tel = srv.stats().get("device_telemetry") or {}
        for got, want in zip(toks, mono_ref):
            assert np.array_equal(got, want)
        # 4 distinct prompts with E=3 entries: >= 4 chunk jobs (a
        # repeat of an LRU-evicted prompt re-chunks, timing-
        # dependent); each job walks every phase over every chunk
        # cursor exactly once
        assert stats["chunked_prefill"] is True
        assert stats["chunk_jobs"] >= 4
        assert stats["chunk_ticks"] == stats["chunk_jobs"] * NC * NPH
        # device counter agrees with the host count (PTA180 contract:
        # the counters live in slot state and ride the dispatch RMW)
        if "prefill_chunks" in tel:
            assert tel["prefill_chunks"] == stats["chunk_ticks"]

    def test_shorts_complete_while_long_prompt_chunks_in(self, built):
        """The latency contract chunking buys: a cold prompt's
        NC x NPH chunk dispatches interleave 1:1 with decode bursts,
        so warm (prefix-hit) requests admitted alongside it finish
        first instead of waiting out the whole prefill."""
        done = {}
        with _server(built) as srv:
            warm = built["prompts"][0]
            srv.submit(warm).result(120.0)      # entry now cached
            f_cold = srv.submit(built["prompts"][3])
            f_hits = [srv.submit(warm) for _ in range(2)]
            f_cold.add_done_callback(
                lambda f: done.setdefault("cold", time.monotonic()))
            for i, f in enumerate(f_hits):
                f.add_done_callback(
                    lambda f, i=i: done.setdefault(i, time.monotonic()))
            f_cold.result(120.0)
            for f in f_hits:
                f.result(120.0)
            stats = srv.pool_stats()
        assert stats["chunk_jobs"] == 2        # warm once, cold once
        assert max(done[i] for i in range(2)) < done["cold"]

    def test_second_wave_compiles_nothing(self, built):
        exe = built["exe"]
        with _server(built) as srv:
            first = _wave(srv, built)
            warmed = exe.compile_count
            second = _wave(srv, built)
            assert exe.compile_count == warmed
        # the repeat wave re-admits through hit/radix tiers — same
        # deterministic tokens
        for got, want in zip(second, first):
            assert np.array_equal(got, want)


class TestPlainRadixReuse:
    def test_sessionless_resubmit_rides_radix_tier(self, built):
        p = np.random.RandomState(11).randint(
            3, V, (1, S)).astype(np.int64)
        with _server(built) as srv:
            t1 = np.asarray(srv.submit(p).result(120.0))
            s1 = srv.pool_stats()
            t2 = np.asarray(srv.submit(p).result(120.0))
            s2 = srv.pool_stats()
        assert s1["plain_radix_admissions"] == 0
        assert s2["plain_radix_admissions"] >= 1
        assert s2["radix_hit_blocks"] > s1["radix_hit_blocks"]
        assert np.array_equal(t1, t2)


class TestDisaggUnsharded:
    """The scope-split half of disaggregation without mesh plans:
    worker prefills on its OWN scope, handoff rows land in the decode
    scope token-exactly. The sharded phase-plan half (different
    ShardingPlans, disjoint device slices) is test_disagg_serving.py
    (slow lane)."""

    def test_worker_fed_server_token_identical(self, built, mono_ref):
        pre_scope = Scope()
        worker = DisaggregatedPrefillWorker(
            built["bundle"], executor=built["exe"], scope=pre_scope,
            params_from=built["scope"])
        try:
            with _server(built, prefill_worker=worker) as srv:
                toks = _wave(srv, built)
                stats = srv.pool_stats()
        finally:
            worker.close()
        for got, want in zip(toks, mono_ref):
            assert np.array_equal(got, want)
        assert stats["disaggregated"] is True
        assert stats["chunk_jobs"] >= 4
        assert stats["disagg_handoffs"] == stats["chunk_jobs"]
        assert stats["disagg_outstanding"] == 0
        ws = worker.stats()
        assert ws["jobs_done"] == stats["chunk_jobs"]
        assert ws["jobs_failed"] == 0
        assert ws["chunk_ticks"] == ws["jobs_done"] * NC * NPH

    def test_worker_contradicts_unchunked_scheduling(self, built):
        fake = types.SimpleNamespace(bundle=built["bundle"])
        with pytest.raises(ValueError, match="implies chunked"):
            _server(built, prefill_worker=fake,
                    chunked_prefill=False)

    def test_worker_must_serve_same_bundle(self, built):
        fake = types.SimpleNamespace(bundle=object())
        with pytest.raises(ValueError, match="SAME bundle"):
            _server(built, prefill_worker=fake)

    def test_worker_needs_chunked_bundle(self, built):
        with unique_name.guard():
            plain = T.build_decode_step_program(
                n_slots=2, admit_buckets=[1], state_prefix="@cpu/",
                seq_len=S, max_out_len=MAXT, d_model=D, n_heads=H,
                n_layers=1, d_inner=64, vocab=V, start_id=2, end_id=1,
                cache=CacheConfig(layout="paged", block_size=BS,
                                  n_blocks=8, n_prompt_entries=2))
        with pytest.raises(ValueError, match="chunk"):
            DisaggregatedPrefillWorker(plain, executor=built["exe"],
                                       scope=Scope(), start=False)
