"""CSP `go` op (reference operators/csp/go_op.cc GoOp — the last
missing-list item from VERDICT r4): a Go block's ops run on a detached
thread against a snapshot of the scope, fire-and-forget, while the
main program runs normally. The reference at this version has no
channel surface left, so host-side-effecting ops (py_func) are the
observable contract."""
import time

import numpy as np

import paddle_tpu as fluid


def _wait_threads(exe, timeout=10.0):
    for t in getattr(exe, "_go_threads", []):
        t.join(timeout)
        assert not t.is_alive(), "go thread did not finish"


class TestGoOp:
    def test_go_block_runs_on_thread_with_scope_snapshot(self):
        seen = []

        def record(arr):
            seen.append(np.asarray(arr).copy())
            return np.asarray(arr)

        prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(prog, startup):
            x = fluid.layers.data("x", shape=[4], dtype="float32")
            y = fluid.layers.scale(x, scale=2.0)
            with fluid.layers.Go():
                doubled = fluid.layers.scale(y, scale=3.0)
                sink = prog.current_block().create_var(
                    name="go_sink", shape=[-1, 4], dtype="float32")
                fluid.layers.py_func(record, doubled, out=sink)
            loss = fluid.layers.mean(y)
        exe = fluid.Executor(fluid.CPUPlace())
        sc = fluid.Scope()
        exe.run(startup, scope=sc)
        xs = np.arange(8, dtype=np.float32).reshape(2, 4)
        out, = exe.run(prog, feed={"x": xs}, fetch_list=[loss],
                       scope=sc)
        # main program unaffected by the go block
        np.testing.assert_allclose(float(np.asarray(out).reshape(-1)[0]),
                                   2.0 * xs.mean(), rtol=1e-6)
        _wait_threads(exe)
        assert len(seen) == 1
        np.testing.assert_allclose(seen[0], 6.0 * xs, rtol=1e-6)

    def test_go_env_is_discarded(self):
        """Writes inside the Go block must NOT leak into the scope
        (the reference destroys the thread's child scope)."""
        prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(prog, startup):
            x = fluid.layers.data("x", shape=[4], dtype="float32")
            with fluid.layers.Go():
                fluid.layers.scale(x, scale=5.0)
            loss = fluid.layers.mean(x)
        exe = fluid.Executor(fluid.CPUPlace())
        sc = fluid.Scope()
        exe.run(startup, scope=sc)
        exe.run(prog, feed={"x": np.ones((2, 4), np.float32)},
                fetch_list=[loss], scope=sc)
        _wait_threads(exe)
        go_op = next(op for op in prog.global_block.ops
                     if op.type == "go")
        sub = go_op.attrs["sub_block"]
        for op in sub.ops:
            for n in op.output_arg_names:
                assert sc._get(n) is None, n

    def test_go_survives_state_donation_across_steps(self):
        """The snapshot must COPY donated state buffers: a Go block
        capturing an activation computed from trainable params runs
        every step while the jitted step donates those params'
        buffers (regression: bare references died silently)."""
        logged = []

        def log(arr):
            logged.append(np.asarray(arr).copy())
            return np.asarray(arr)

        prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(prog, startup):
            x = fluid.layers.data("x", shape=[8], dtype="float32")
            y = fluid.layers.data("y", shape=[1], dtype="int64")
            logits = fluid.layers.fc(x, 3)
            with fluid.layers.Go():
                sink = prog.current_block().create_var(
                    name="sink3", shape=[-1, 3], dtype="float32")
                fluid.layers.py_func(log, logits, out=sink)
            loss = fluid.layers.mean(
                fluid.layers.softmax_with_cross_entropy(logits, y))
            fluid.optimizer.Adam(0.05).minimize(loss)
        exe = fluid.Executor(fluid.CPUPlace())
        sc = fluid.Scope()
        exe.run(startup, scope=sc)
        r = np.random.RandomState(0)
        feed = {"x": r.randn(16, 8).astype(np.float32),
                "y": r.randint(0, 3, (16, 1)).astype(np.int64)}
        for _ in range(8):
            exe.run(prog, feed=feed, fetch_list=[loss], scope=sc)
        _wait_threads(exe)
        assert len(logged) == 8
        # and they track training (params changed between snapshots)
        assert np.abs(logged[-1] - logged[0]).max() > 0

    def test_go_fires_every_run(self):
        calls = []

        def bump(arr):
            calls.append(1)
            return np.asarray(arr)

        prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(prog, startup):
            x = fluid.layers.data("x", shape=[2], dtype="float32")
            with fluid.layers.Go():
                sink = prog.current_block().create_var(
                    name="sink2", shape=[-1, 2], dtype="float32")
                fluid.layers.py_func(bump, x, out=sink)
            loss = fluid.layers.mean(x)
        exe = fluid.Executor(fluid.CPUPlace())
        sc = fluid.Scope()
        exe.run(startup, scope=sc)
        for _ in range(3):
            exe.run(prog, feed={"x": np.ones((2, 2), np.float32)},
                    fetch_list=[loss], scope=sc)
        deadline = time.time() + 10
        while len(calls) < 3 and time.time() < deadline:
            time.sleep(0.05)
        _wait_threads(exe)
        assert len(calls) == 3


class TestGoProducerOrdering:
    """ADVICE r5: the recompute-chain producer map must see only ops
    BEFORE the go op in block order; later-positioned or multi-writer
    producers are named errors (the reference's eager executor would
    never observe those values at the go point)."""

    def test_producer_after_go_op_is_named_error(self):
        prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(prog, startup):
            x = fluid.layers.data("x", shape=[4], dtype="float32")
            y = fluid.layers.scale(x, scale=2.0)
            with fluid.layers.Go():
                fluid.layers.scale(y, scale=3.0)
            loss = fluid.layers.mean(x)
        ops = prog.global_block.ops
        y_i = next(i for i, o in enumerate(ops)
                   if y.name in o.output_arg_names)
        go_i = next(i for i, o in enumerate(ops) if o.type == "go")
        assert y_i < go_i
        # move y's producer AFTER the go op: the go thread would
        # recompute a value the eager executor never saw at this point
        ops.append(ops.pop(y_i))
        prog._version += 1
        exe = fluid.Executor(fluid.CPUPlace())
        sc = fluid.Scope()
        exe.run(startup, scope=sc)
        import pytest
        with pytest.raises(RuntimeError,
                           match="AFTER the go op"):
            exe.run(prog, feed={"x": np.ones((2, 4), np.float32)},
                    fetch_list=[loss], scope=sc)

    def test_multi_writer_before_go_is_named_error(self):
        prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(prog, startup):
            x = fluid.layers.data("x", shape=[4], dtype="float32")
            y = fluid.layers.scale(x, scale=2.0)
            # second in-place writer of y before the go op: the
            # recompute chain can't know which value the go captured
            prog.global_block.append_op(
                "scale", {"X": [y.name]}, {"Out": [y.name]},
                {"scale": 5.0})
            with fluid.layers.Go():
                fluid.layers.scale(y, scale=3.0)
            loss = fluid.layers.mean(x)
        exe = fluid.Executor(fluid.CPUPlace())
        sc = fluid.Scope()
        exe.run(startup, scope=sc)
        import pytest
        with pytest.raises(RuntimeError,
                           match="multiple writers"):
            exe.run(prog, feed={"x": np.ones((2, 4), np.float32)},
                    fetch_list=[loss], scope=sc)
