"""Read-only protobuf ProgramDesc importer (VERDICT r4 next #6):
a reference-saved ``__model__`` (+ reference-format LoDTensor param
files) loads through fluid.io.load_inference_model and runs through
the Executor.

The fixture ``tests/fixtures/mnist_fc_program.__model__`` is encoded
from the hand-authored textproto next to it with protoc AGAINST THE
REFERENCE'S OWN framework.proto::

    protoc -I <ref>/paddle/fluid/framework \
      --encode=paddle.framework.proto.ProgramDesc \
      <ref>/paddle/fluid/framework/framework.proto \
      < mnist_fc_program.textpb > mnist_fc_program.__model__

so the bytes the importer decodes are genuine reference wire format,
not this repo's own encoder talking to itself."""
import os
import shutil
import struct
import subprocess

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.inference import proto_import as PI

FIXDIR = os.path.join(os.path.dirname(__file__), "fixtures")
MODEL = os.path.join(FIXDIR, "mnist_fc_program.__model__")
TEXTPB = os.path.join(FIXDIR, "mnist_fc_program.textpb")
REF_PROTO_DIR = "/root/reference/paddle/fluid/framework"


def _write_ref_lod_tensor(path, arr):
    """Reference SerializeToStream layout (lod_tensor.cc:246 /
    tensor_util.cc TensorToStream), written independently here so the
    importer is tested against the documented format, not itself."""
    dt = {np.dtype("float32"): 5, np.dtype("int64"): 3,
          np.dtype("float64"): 6, np.dtype("int32"): 2}[arr.dtype]
    # TensorDesc proto: field 1 varint data_type, field 2 packed? --
    # the reference writes unpacked int64 dims (proto2 default)
    desc = bytes([0x08, dt])
    for d in arr.shape:
        desc += bytes([0x10]) + _varint(d)
    out = struct.pack("<I", 0)          # LoDTensor version
    out += struct.pack("<Q", 0)         # lod levels
    out += struct.pack("<I", 0)         # Tensor version
    out += struct.pack("<i", len(desc))
    out += desc
    out += arr.tobytes()
    with open(path, "wb") as f:
        f.write(out)


def _varint(x):
    out = b""
    while True:
        b = x & 0x7F
        x >>= 7
        if x:
            out += bytes([b | 0x80])
        else:
            return out + bytes([b])


class TestWireParsing:
    def test_fixture_parses_to_expected_program(self):
        with open(MODEL, "rb") as f:
            raw = f.read()
        assert PI.is_program_desc(raw)
        prog = PI.parse_program_desc(raw)
        blk = prog.global_block
        assert [op.type for op in blk.ops] == [
            "feed", "mul", "elementwise_add", "softmax", "fetch"]
        assert blk.var("fc_w").shape == (8, 4)
        assert blk.var("fc_w").persistable
        assert blk.var("img").shape == (-1, 8)
        assert blk.var("img").dtype.value == "float32"
        assert blk.var("img").is_data  # fed by the feed op
        mul = blk.ops[1]
        assert mul.attrs["x_num_col_dims"] == 1
        feeds, fetches = PI.feed_fetch_names(prog)
        assert feeds == ["img"] and fetches == ["softmax_out"]

    def test_attr_wire_types_decode(self):
        with open(MODEL, "rb") as f:
            prog = PI.parse_program_desc(f.read())
        sm = prog.global_block.ops[3]
        assert sm.attrs["use_cudnn"] is True
        assert sm.attrs["data_format"] == "AnyLayout"
        assert sm.attrs["op_role_var"] == ["a", "b"]
        np.testing.assert_allclose(sm.attrs["wire_floats"],
                                   [0.5, -1.25])
        assert sm.attrs["wire_longs"] == [7, -9]
        assert sm.attrs["wire_bools"] == [True, False]
        assert sm.attrs["wire_long"] == 1234567890123

    @pytest.mark.skipif(
        shutil.which("protoc") is None
        or not os.path.exists(os.path.join(REF_PROTO_DIR,
                                           "framework.proto")),
        reason="protoc or the reference proto unavailable")
    def test_fixture_bytes_match_reference_schema_encoding(self):
        """Guard against fixture drift: re-encoding the textproto with
        the reference's own .proto reproduces the committed bytes."""
        with open(TEXTPB, "rb") as f:
            enc = subprocess.run(
                ["protoc", "-I", REF_PROTO_DIR,
                 "--encode=paddle.framework.proto.ProgramDesc",
                 os.path.join(REF_PROTO_DIR, "framework.proto")],
                input=f.read(), capture_output=True, check=True)
        with open(MODEL, "rb") as f:
            assert enc.stdout == f.read()


class TestEndToEnd:
    def test_reference_model_dir_loads_and_runs(self, tmp_path):
        """The verdict's done-bar: the imported program runs through
        the Executor — via the USER API (load_inference_model on a
        reference-layout dir with reference-format param files)."""
        fluid._reset_global_scope()
        d = str(tmp_path / "ref_model")
        os.makedirs(d)
        shutil.copy(MODEL, os.path.join(d, "__model__"))
        r = np.random.RandomState(0)
        w = r.randn(8, 4).astype(np.float32)
        b = r.randn(4).astype(np.float32)
        _write_ref_lod_tensor(os.path.join(d, "fc_w"), w)
        _write_ref_lod_tensor(os.path.join(d, "fc_b"), b)

        exe = fluid.Executor(fluid.CPUPlace())
        prog, feed_names, fetch_targets = fluid.io.load_inference_model(
            d, exe)
        assert feed_names == ["img"]
        x = r.randn(16, 8).astype(np.float32)
        out, = exe.run(prog, feed={"img": x},
                       fetch_list=fetch_targets)
        # numpy oracle
        logits = x @ w + b
        e = np.exp(logits - logits.max(1, keepdims=True))
        want = e / e.sum(1, keepdims=True)
        np.testing.assert_allclose(np.asarray(out), want,
                                   rtol=1e-5, atol=1e-6)

    def test_combined_params_file_loads(self, tmp_path):
        """The reference's save_combine layout (one __params__ file of
        concatenated LoDTensor streams) loads via params_filename."""
        fluid._reset_global_scope()
        d = str(tmp_path / "ref_combined")
        os.makedirs(d)
        shutil.copy(MODEL, os.path.join(d, "__model__"))
        r = np.random.RandomState(3)
        w = r.randn(8, 4).astype(np.float32)
        b = r.randn(4).astype(np.float32)
        p_w, p_b = str(tmp_path / "w"), str(tmp_path / "b")
        _write_ref_lod_tensor(p_w, w)
        _write_ref_lod_tensor(p_b, b)
        # the reference's save_combine writes streams sorted by var
        # name (reference io.py:203 `for name in sorted(save_var_map
        # .keys())`): fc_b BEFORE fc_w, even though the program
        # declares fc_w first
        with open(os.path.join(d, "__params__"), "wb") as f:
            for p in (p_b, p_w):
                with open(p, "rb") as g:
                    f.write(g.read())
        exe = fluid.Executor(fluid.CPUPlace())
        prog, feed_names, fetch_targets = fluid.io.load_inference_model(
            d, exe, params_filename="__params__")
        x = r.randn(8, 8).astype(np.float32)
        out, = exe.run(prog, feed={"img": x}, fetch_list=fetch_targets)
        logits = x @ w + b
        e = np.exp(logits - logits.max(1, keepdims=True))
        np.testing.assert_allclose(np.asarray(out),
                                   e / e.sum(1, keepdims=True),
                                   rtol=1e-5, atol=1e-6)

    def test_lod_tensor_roundtrip_with_lod_metadata(self, tmp_path):
        """LoD offsets in the stream are skipped, payload intact."""
        arr = np.arange(12, dtype=np.int64).reshape(3, 4)
        path = str(tmp_path / "t")
        # write with one fake LoD level to exercise the skip path
        desc = bytes([0x08, 3]) + bytes([0x10, 3, 0x10, 4])
        lod = np.asarray([0, 2, 3], dtype=np.uint64)
        blob = (struct.pack("<I", 0) + struct.pack("<Q", 1)
                + struct.pack("<Q", lod.nbytes) + lod.tobytes()
                + struct.pack("<I", 0) + struct.pack("<i", len(desc))
                + desc + arr.tobytes())
        with open(path, "wb") as f:
            f.write(blob)
        with open(path, "rb") as f:
            got = PI.parse_lod_tensor(f.read())
        np.testing.assert_array_equal(got, arr)
