"""Native XLA-computation builder (native/xla_train/xla_train.cc):
the MNIST-fc train step's XLA program is BUILT in C++ by per-op
registry kernels over the native ProgramDesc — closing SURVEY §2's [N]
obligation for kernel registration/dispatch (reference
framework/op_registry.h:197-270) — and trained with no Python in the
process. The Python Executor is the numerical oracle: per-step losses
must match to 1e-5 (VERDICT r3 next #3's done-bar)."""
import os

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import native


def _fresh():
    fluid._reset_global_scope()
    from paddle_tpu import unique_name
    unique_name.switch()


def _build_mnist_fc():
    prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(prog, startup):
        x = fluid.layers.data(name="img", shape=[784],
                              dtype="float32")
        y = fluid.layers.data(name="label", shape=[1], dtype="int64")
        h = fluid.layers.fc(x, size=128, act="relu",
                            param_attr=fluid.ParamAttr(name="fc1_w"),
                            bias_attr=fluid.ParamAttr(name="fc1_b"))
        logits = fluid.layers.fc(
            h, size=10, param_attr=fluid.ParamAttr(name="fc2_w"),
            bias_attr=fluid.ParamAttr(name="fc2_b"))
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, y))
        fluid.optimizer.SGD(0.5).minimize(loss)
    return prog, startup, loss


def _data(B=64, seed=0):
    r = np.random.RandomState(seed)
    img = r.randn(B, 784).astype(np.float32) * 0.1
    # separable synthetic task so the loss genuinely falls
    w_true = r.randn(784, 10).astype(np.float32)
    label = np.argmax(img @ w_true, 1).astype(np.int64)[:, None]
    return {"img": img, "label": label}


def _native_ready():
    try:
        native.build_xla_train()
        return True
    except RuntimeError:
        return False


@pytest.mark.skipif(not _native_ready(),
                    reason="no toolchain/XLA runtime for xla_train")
class TestNativeXlaBuilder:
    def test_mnist_fc_losses_match_python_to_1e5(self, tmp_path):
        _fresh()
        feed = _data()
        prog, startup, loss = _build_mnist_fc()
        exe = fluid.Executor(fluid.CPUPlace())
        sc = fluid.Scope()
        exe.run(startup, scope=sc)

        # export FIRST (the artifact must hold step-0 state), then run
        # the Python oracle from the same scope
        from paddle_tpu.inference.export import export_train_program
        art = export_train_program(prog, sc, feed, [loss.name],
                                   str(tmp_path / "mnist_native"))

        steps = 6
        py_losses = []
        for _ in range(steps):
            l, = exe.run(prog, feed=feed, fetch_list=[loss], scope=sc)
            py_losses.append(float(np.asarray(l).reshape(-1)[0]))

        rows = native.run_xla_train(art, steps)
        native_losses = [row[loss.name] for row in rows]
        assert len(native_losses) == steps
        np.testing.assert_allclose(native_losses, py_losses,
                                   rtol=1e-5, atol=1e-6)
        assert py_losses[-1] < py_losses[0]  # and it actually trains

    def test_final_state_written_and_close_to_python(self, tmp_path):
        _fresh()
        feed = _data(seed=1)
        prog, startup, loss = _build_mnist_fc()
        exe = fluid.Executor(fluid.CPUPlace())
        sc = fluid.Scope()
        exe.run(startup, scope=sc)
        from paddle_tpu.inference.export import export_train_program
        art = export_train_program(prog, sc, feed, [loss.name],
                                   str(tmp_path / "m2"))
        steps = 4
        for _ in range(steps):
            exe.run(prog, feed=feed, fetch_list=[loss], scope=sc)
        native.run_xla_train(art, steps)
        # fc1_w final state must match the Python-trained weights
        import json
        with open(os.path.join(art, "manifest.json")) as f:
            manifest = json.load(f)
        spec = next(s for s in manifest["inputs"]
                    if s["name"] == "fc1_w")
        final = np.fromfile(os.path.join(art, spec["file"] + ".final"),
                            dtype=spec["dtype"]).reshape(spec["shape"])
        np.testing.assert_allclose(final, np.asarray(sc._get("fc1_w")),
                                   rtol=1e-5, atol=1e-6)

    def test_unregistered_op_is_a_named_error(self, tmp_path):
        _fresh()
        prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(prog, startup):
            x = fluid.layers.data(name="x", shape=[8],
                                  dtype="float32")
            # atan has no native kernel registered (tanh does — the r4
            # version of this test used tanh and only passed against a
            # stale committed binary, ADVICE r4 #1)
            out = fluid.layers.atan(x)
        exe = fluid.Executor(fluid.CPUPlace())
        sc = fluid.Scope()
        exe.run(startup, scope=sc)
        from paddle_tpu.inference.export import export_train_program
        art = export_train_program(
            prog, sc, {"x": np.zeros((2, 8), np.float32)},
            [out.name], str(tmp_path / "m3"))
        with pytest.raises(RuntimeError,
                           match="no native XLA kernel registered"):
            native.run_xla_train(art, 1)

    def test_split_with_inferred_section(self, tmp_path):
        """A -1 entry in split's `sections` (one inferred section,
        allowed by the fluid API) must resolve from the axis extent in
        the native kernel instead of handing SliceInDim a negative
        bound (ADVICE r5); parity vs the Python executor."""
        _fresh()
        prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(prog, startup):
            x = fluid.layers.data(name="x", shape=[6],
                                  dtype="float32")
            a, b = fluid.layers.split(x, [2, -1], dim=1)
            loss = fluid.layers.mean(a) + fluid.layers.mean(b)
        exe = fluid.Executor(fluid.CPUPlace())
        sc = fluid.Scope()
        exe.run(startup, scope=sc)
        feed = {"x": np.arange(12, dtype=np.float32).reshape(2, 6)}
        from paddle_tpu.inference.export import export_train_program
        art = export_train_program(prog, sc, feed, [loss.name],
                                   str(tmp_path / "m_split"))
        py, = exe.run(prog, feed=feed, fetch_list=[loss], scope=sc)
        rows = native.run_xla_train(art, 1)
        np.testing.assert_allclose(
            rows[0][loss.name],
            float(np.asarray(py).reshape(-1)[0]), rtol=1e-6)
