"""Book-model parity, part 2: fit_a_line, image_classification (vgg),
machine_translation / rnn_encoder_decoder.

Parity model: reference tests/book/test_fit_a_line.py,
test_image_classification.py, test_machine_translation.py,
test_rnn_encoder_decoder.py -- each trains a real small model to a
falling loss, exports with save_inference_model, reloads and infers
(the reference's checkpoint-round-trip double duty, SURVEY.md §4.4).
"""
import numpy as np
import pytest

import paddle_tpu as fluid


from test_book_models import _run


def _train(prog, startup, cost, feeds, steps, scope=None):
    return _run(prog, startup, cost, feeds, steps, scope=scope,
                return_exe=True)


class TestFitALine:
    """reference book/test_fit_a_line.py: 13-feature linear
    regression (UCI housing shape), SGD."""

    def test_trains_and_roundtrips(self, tmp_path):
        rng = np.random.RandomState(0)
        true_w = rng.randn(13, 1).astype("float32")
        x_np = rng.rand(64, 13).astype("float32")
        y_np = x_np @ true_w + 0.1

        prog = fluid.Program()
        startup = fluid.Program()
        with fluid.program_guard(prog, startup):
            x = fluid.layers.data("x", shape=(13,), dtype="float32")
            y = fluid.layers.data("y", shape=(1,), dtype="float32")
            pred = fluid.layers.fc(x, size=1)
            cost = fluid.layers.mean(
                fluid.layers.square_error_cost(pred, y))
            test_prog = prog.clone(for_test=True)
            fluid.optimizer.SGD(learning_rate=0.05).minimize(cost)

        fluid._reset_global_scope()
        # save/load_inference_model read params from the global scope
        exe, losses = _train(prog, startup, cost.name,
                             {"x": x_np, "y": y_np}, 60,
                             scope=fluid.global_scope())
        assert losses[-1] < losses[0] * 0.1, losses[::20]

        pred_before = np.asarray(exe.run(
            test_prog, feed={"x": x_np, "y": y_np},
            fetch_list=[pred.name])[0])
        path = str(tmp_path / "fit_a_line")
        fluid.save_inference_model(
            path, ["x"], [test_prog.global_block.var(pred.name)], exe,
            main_program=test_prog)
        prog2, feed_names, fetch_names = fluid.load_inference_model(
            path, exe)
        pred_after = np.asarray(exe.run(
            prog2, feed={feed_names[0]: x_np},
            fetch_list=fetch_names)[0])
        np.testing.assert_allclose(pred_after, pred_before,
                                   atol=1e-5, rtol=1e-5)


class TestImageClassificationVGG:
    """reference book/test_image_classification.py (vgg flavor),
    cifar-shaped 3x32x32 input (vgg's 5 pool halvings need >=32)."""

    def test_trains(self):
        from paddle_tpu.models import vgg

        rng = np.random.RandomState(1)
        prog, startup, cost = vgg.build_program(
            class_dim=4, image_shape=(3, 32, 32), lr=0.01)
        img = rng.rand(8, 3, 32, 32).astype("float32")
        lbl = rng.randint(0, 4, (8, 1)).astype("int64")
        scope = fluid.Scope()
        _, losses = _train(prog, startup, cost,
                           {"img": img, "label": lbl}, 15, scope)
        assert losses[-1] < losses[0], (losses[0], losses[-1])


class TestMachineTranslation:
    """reference book/test_machine_translation.py +
    test_rnn_encoder_decoder.py: gru seq2seq with attention-era
    decode; trains with falling loss."""

    def test_trains(self):
        from paddle_tpu.models import machine_translation as mt

        rng = np.random.RandomState(2)
        prog, startup, cost = mt.build_program(
            src_dict_dim=60, tgt_dict_dim=60)
        b, t = 8, 10
        feeds = {
            "src_word_id": rng.randint(1, 60, (b, t)).astype("int64"),
            "target_language_word":
                rng.randint(1, 60, (b, t)).astype("int64"),
            "target_language_next_word":
                rng.randint(1, 60, (b, t)).astype("int64"),
            "src_word_id@SEQ_LEN":
                rng.randint(3, t + 1, (b,)).astype("int32"),
            "target_language_word@SEQ_LEN":
                rng.randint(3, t + 1, (b,)).astype("int32"),
        }
        missing = [n for n in feeds if n not in prog.global_block.vars]
        assert not missing, f"model builder renamed feeds: {missing}"
        scope = fluid.Scope()
        _, losses = _train(prog, startup, cost, feeds, 12, scope)
        assert losses[-1] < losses[0], (losses[0], losses[-1])


if __name__ == "__main__":
    pytest.main([__file__, "-q"])
