"""Dataset package + reader decorator tests.

Parity model: reference python/paddle/reader/tests/decorator_test.py and
python/paddle/dataset/tests/*_test.py (shape/dtype/range assertions).
"""
import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import dataset, readers


class TestDatasets:
    def test_mnist_shapes(self):
        it = dataset.mnist.train()()
        img, lab = next(it)
        assert img.shape == (784,) and img.dtype == np.float32
        assert -1.0 <= img.min() and img.max() <= 1.0
        assert 0 <= lab < 10

    def test_mnist_deterministic(self):
        first = [(img.sum(), lab) for _, (img, lab) in
                 zip(range(10), dataset.mnist.train()())]
        second = [(img.sum(), lab) for _, (img, lab) in
                  zip(range(10), dataset.mnist.train()())]
        assert first == second

    def test_cifar(self):
        img, lab = next(dataset.cifar.train10()())
        assert img.shape == (3072,)
        assert 0 <= lab < 10
        img, lab = next(dataset.cifar.train100()())
        assert 0 <= lab < 100

    def test_uci_housing_linear_structure(self):
        xs, ys = [], []
        for x, y in dataset.uci_housing.train()():
            xs.append(x)
            ys.append(y[0])
        X = np.stack(xs)
        Y = np.array(ys)
        w, *_ = np.linalg.lstsq(
            np.concatenate([X, np.ones((len(X), 1))], 1), Y, rcond=None)
        resid = Y - np.concatenate([X, np.ones((len(X), 1))], 1) @ w
        assert np.std(resid) < 2.0  # learnable linear signal

    def test_imdb(self):
        wd = dataset.imdb.word_dict()
        assert "<unk>" in wd
        ids, lab = next(dataset.imdb.train(wd)())
        assert all(0 <= i < len(wd) for i in ids)
        assert lab in (0, 1)

    def test_wmt14(self):
        src, trg_in, trg_next = next(dataset.wmt14.train(1000)())
        assert trg_in[0] == dataset.wmt14.START_ID
        assert trg_next[-1] == dataset.wmt14.END_ID
        assert trg_in[1:] == trg_next[:-1]
        sd, td = dataset.wmt14.get_dict(1000)
        assert len(sd) == 1000 and len(td) == 1000

    def test_movielens(self):
        item = next(dataset.movielens.train()())
        uid, gender, age, job, mid, cats, title, score = item
        assert 1 <= uid <= dataset.movielens.max_user_id()
        assert 1 <= mid <= dataset.movielens.max_movie_id()
        assert 1.0 <= score[0] <= 5.0

    def test_conll05(self):
        wd, vd, ld = dataset.conll05.get_dict()
        item = next(dataset.conll05.test()())
        assert len(item) == 9
        length = len(item[0])
        assert all(len(s) == length for s in item)
        assert sum(item[7]) == 1  # exactly one predicate mark

    def test_flowers(self):
        img, lab = next(dataset.flowers.train()())
        assert img.shape == (3 * 224 * 224,)
        assert 0 <= lab < 102

    def test_image_transforms(self):
        im = np.arange(40 * 60 * 3, dtype=np.float32).reshape(40, 60, 3)
        out = dataset.image.resize_short(im, 32)
        assert min(out.shape[:2]) == 32
        out = dataset.image.simple_transform(im, 36, 32, is_train=False)
        assert out.shape == (3, 32, 32)


class TestReaderDecorators:
    def _range_reader(self, n):
        def reader():
            return iter(range(n))

        return reader

    def test_batch(self):
        b = readers.batch(self._range_reader(10), 3)
        batches = list(b())
        assert batches == [[0, 1, 2], [3, 4, 5], [6, 7, 8], [9]]
        b = readers.batch(self._range_reader(10), 3, drop_last=True)
        assert len(list(b())) == 3

    def test_shuffle_preserves_items(self):
        out = list(readers.shuffle(self._range_reader(20), 5, seed=1)())
        assert sorted(out) == list(range(20))

    def test_buffered(self):
        out = list(readers.buffered(self._range_reader(50), 8)())
        assert out == list(range(50))

    def test_buffered_propagates_errors(self):
        def bad():
            yield 1
            raise ValueError("boom")

        with pytest.raises(ValueError):
            list(readers.buffered(lambda: bad(), 2)())

    def test_compose_chain_firstn(self):
        r1 = self._range_reader(3)
        r2 = lambda: iter("abc")  # noqa: E731
        assert list(readers.compose(r1, r2)()) == [(0, "a"), (1, "b"),
                                                   (2, "c")]
        assert list(readers.chain(r1, r1)()) == [0, 1, 2, 0, 1, 2]
        assert list(readers.firstn(self._range_reader(100), 4)()) == \
            [0, 1, 2, 3]

    def test_map_readers(self):
        out = list(readers.map_readers(lambda a, b: a + b,
                                       self._range_reader(3),
                                       self._range_reader(3))())
        assert out == [0, 2, 4]

    def test_cache(self):
        calls = [0]

        def src():
            calls[0] += 1
            return iter(range(5))

        r = readers.cache(src)
        assert list(r()) == list(range(5))
        assert list(r()) == list(range(5))
        assert calls[0] == 1

    def test_xmap_ordered(self):
        out = list(readers.xmap_readers(lambda x: x * 2,
                                        self._range_reader(30), 4, 8,
                                        order=True)())
        assert out == [x * 2 for x in range(30)]

    def test_xmap_unordered(self):
        out = list(readers.xmap_readers(lambda x: x * 2,
                                        self._range_reader(30), 4, 8)())
        assert sorted(out) == [x * 2 for x in range(30)]

    def test_multiprocess_reader(self):
        out = list(readers.multiprocess_reader(
            [self._range_reader(10), self._range_reader(10)])())
        assert sorted(out) == sorted(list(range(10)) * 2)

    def test_batch_exposed_at_top_level(self):
        assert fluid.batch is readers.batch

    def test_xmap_propagates_mapper_error(self):
        def bad_map(x):
            if x == 5:
                raise ValueError("boom")
            return x

        with pytest.raises(ValueError):
            list(readers.xmap_readers(bad_map, self._range_reader(10),
                                      2, 4, order=True)())

    def test_multiprocess_propagates_reader_error(self):
        def bad():
            yield 1
            raise ValueError("boom")

        with pytest.raises(ValueError):
            list(readers.multiprocess_reader(
                [self._range_reader(5), lambda: bad()])())

    def test_cache_partial_first_pass_not_corrupted(self):
        r = readers.cache(self._range_reader(5))
        assert list(readers.firstn(r, 3)()) == [0, 1, 2]
        assert list(r()) == list(range(5))
        assert list(r()) == list(range(5))

    def test_compose_off_by_one_detected(self):
        with pytest.raises(readers.ComposeNotAligned):
            list(readers.compose(self._range_reader(4),
                                 self._range_reader(3))())

    def test_flowers_mapper_applied(self):
        r = dataset.flowers.test(mapper=lambda s: (s[0] * 0 + 1.0, s[1]))
        img, lab = next(r())
        assert float(img.max()) == 1.0 and float(img.min()) == 1.0


class TestEndToEndWithExecutor:
    def test_mnist_reader_feeds_training(self):
        import paddle_tpu.layers as layers

        img = layers.data(name="img", shape=[784], dtype="float32")
        label = layers.data(name="label", shape=[1], dtype="int64")
        fc = layers.fc(input=img, size=10, act="softmax")
        loss = layers.mean(layers.cross_entropy(input=fc, label=label))
        opt = fluid.optimizer.SGDOptimizer(learning_rate=0.5)
        opt.minimize(loss)

        exe = fluid.Executor(fluid.TPUPlace(0))
        exe.run(fluid.default_startup_program())
        feeder = fluid.DataFeeder(feed_list=[img, label])
        train_reader = fluid.batch(
            fluid.readers.shuffle(fluid.dataset.mnist.train(), 500,
                                  seed=0), batch_size=64)
        losses = []
        for i, batch in enumerate(train_reader()):
            if i >= 30:
                break
            out, = exe.run(feed=feeder.feed(batch), fetch_list=[loss])
            losses.append(float(out))
        assert np.mean(losses[-5:]) < np.mean(losses[:5]) - 0.1


def test_imikolov_readers():
    from paddle_tpu.dataset import imikolov

    d = imikolov.build_dict()
    grams = list(imikolov.train(d, 5)())[:50]
    assert all(len(g) == 5 for g in grams)
    vocab = len(d)
    assert all(0 <= w < vocab for g in grams for w in g)
    src, trg = next(iter(imikolov.train(
        d, 5, imikolov.DataType.SEQ)()))
    assert len(src) == len(trg)
    assert src[0] == d["<s>"] and trg[-1] == d["<e>"]
    # deterministic across constructions
    g2 = list(imikolov.train(d, 5)())[:50]
    assert grams == g2


def test_sentiment_and_voc2012_readers():
    from paddle_tpu.dataset import sentiment, voc2012

    wd = sentiment.get_word_dict()
    assert wd[0][1] == 0
    s = list(sentiment.train()())[:20]
    assert all(lab in (0, 1) for _, lab in s)
    img, lab = next(iter(voc2012.train()()))
    assert img.shape[0] == 3 and img.shape[1:] == lab.shape
    assert 0 <= lab.max() < voc2012.CLASS_NUM


def test_mq2007_formats():
    from paddle_tpu.dataset import mq2007

    f, r = next(iter(mq2007.train("pointwise")()))
    assert f.shape == (mq2007.FEATURE_DIM,) and r in (0, 1, 2)
    a, b = next(iter(mq2007.train("pairwise")()))
    assert a.shape == b.shape == (mq2007.FEATURE_DIM,)
    labels, feats = next(iter(mq2007.train("listwise")()))
    assert len(labels) == len(feats)


def test_reader_decorator_parity_extras():
    """ComposeNotAligned / PipeReader / Fake (reference
    python/paddle/reader/decorator.py:145,460,531)."""
    import pytest

    from paddle_tpu import readers

    def r3():
        yield from range(3)

    def r4():
        yield from range(4)

    with pytest.raises(readers.ComposeNotAligned):
        list(readers.compose(r3, r4)())
    # Fake: caches first item, replays it data_num times
    fake = readers.Fake()(r3, 5)
    assert list(fake()) == [0] * 5
    assert list(fake()) == [0] * 5  # resets after a full pass
    # PipeReader: stream a real command's stdout
    pr = readers.PipeReader("printf a\\nb\\nc\\n")
    lines = list(pr.get_line())
    assert lines == ["a", "b", "c"]
    with pytest.raises(TypeError):
        readers.PipeReader(["not", "a", "string"])


def test_reader_decorator_review_regressions(tmp_path):
    import gzip
    import os

    import pytest

    from paddle_tpu import readers

    # multi-member gzip: both members' lines come through
    p1 = os.path.join(str(tmp_path), "a.gz")
    with open(p1, "wb") as f:
        f.write(gzip.compress(b"one\ntwo\n") +
                gzip.compress(b"three\nfour\n"))
    pr = readers.PipeReader(f"cat {p1}", file_type="gzip")
    assert list(pr.get_line()) == ["one", "two", "three", "four"]
    # multibyte char split across the buffer boundary survives
    p2 = os.path.join(str(tmp_path), "utf.txt")
    payload = ("x" * 8191 + "é\n").encode("utf8")  # é straddles 8192
    open(p2, "wb").write(payload)
    lines = list(readers.PipeReader(f"cat {p2}").get_line())
    assert lines == ["x" * 8191 + "é"]
    # failing command raises instead of looking like an empty dataset
    with pytest.raises(IOError):
        list(readers.PipeReader("cat /nonexistent-xyz").get_line())
    # Fake: partial consumption must not shorten later passes
    def r3():
        yield from range(3)
    fake = readers.Fake()(r3, 5)
    it = fake()
    next(it); next(it)
    del it
    assert len(list(fake())) == 5
    with pytest.raises(ValueError):
        list(readers.Fake()(lambda: iter(()), 5)())
