"""C++ train demo: the exported train-step HLO artifact drives real
training from a native process with NO Python (VERDICT r2 #6; the
reference's train/demo/demo_trainer.cc capability).

The parity standard is strict: the C++ driver's per-step losses must
equal the Python Executor's on the same program/weights/feeds.
"""
import os

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import native
from paddle_tpu.inference.export import export_train_hlo


@pytest.fixture(scope="module")
def demo_binary():
    """Lazy: the g++ link against libtensorflow only runs when a test
    in THIS file actually executes, never at collection time."""
    try:
        return native.build_train_demo()
    except RuntimeError as e:
        pytest.skip(f"no g++/XLA runtime for the C++ train demo: {e}")


def _build(seed=13):
    prog, startup = fluid.Program(), fluid.Program()
    prog._seed = seed
    with fluid.program_guard(prog, startup):
        x = fluid.layers.data(name="x", shape=[8], dtype="float32")
        y = fluid.layers.data(name="y", shape=[1], dtype="int64")
        h = fluid.layers.fc(x, size=16, act="tanh")
        logits = fluid.layers.fc(h, size=3)
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, y))
        fluid.optimizer.Adam(0.05).minimize(loss)
    return prog, startup, loss


def _data():
    r = np.random.RandomState(0)
    xs = r.randn(32, 8).astype(np.float32)
    ys = np.argmax(xs[:, :3], 1).astype(np.int64)[:, None]
    return xs, ys


class TestCppTrainDemo:
    def test_losses_match_python_executor(self, tmp_path, demo_binary):
        xs, ys = _data()
        prog, startup, loss = _build()
        exe = fluid.Executor(fluid.CPUPlace())
        sc = fluid.Scope()
        exe.run(startup, scope=sc)

        # export BEFORE training so both drivers start from the same
        # weights
        art = export_train_hlo(prog, sc, {"x": xs, "y": ys},
                               [loss.name], str(tmp_path / "art"))

        py_losses = []
        for _ in range(6):
            l, = exe.run(prog, feed={"x": xs, "y": ys},
                         fetch_list=[loss], scope=sc)
            py_losses.append(float(np.asarray(l).reshape(-1)[0]))

        rows = native.run_train_demo(art, 6)
        cc_losses = [row[loss.name] for row in rows]
        np.testing.assert_allclose(cc_losses, py_losses, rtol=1e-5,
                                   atol=1e-6)
        assert cc_losses[-1] < cc_losses[0]

    def test_final_state_written_and_resumable(self, tmp_path, demo_binary):
        """The driver writes final state; reloading it into a scope
        continues training where C++ left off."""
        xs, ys = _data()
        prog, startup, loss = _build(seed=17)
        exe = fluid.Executor(fluid.CPUPlace())
        sc = fluid.Scope()
        exe.run(startup, scope=sc)
        art = export_train_hlo(prog, sc, {"x": xs, "y": ys},
                               [loss.name], str(tmp_path / "art2"))
        rows = native.run_train_demo(art, 5)

        # load final state back per the manifest
        import json as _json

        with open(os.path.join(art, "manifest.json")) as f:
            manifest = _json.load(f)
        for spec in manifest["inputs"]:
            if spec["kind"] != "state":
                continue
            path = os.path.join(art, spec["file"] + ".final")
            arr = np.fromfile(path, dtype=spec["dtype"]).reshape(
                spec["shape"])
            sc._set(spec["name"], arr)
        l, = exe.run(prog, feed={"x": xs, "y": ys},
                     fetch_list=[loss], scope=sc)
        nxt = float(np.asarray(l).reshape(-1)[0])
        # continues the C++ trajectory: close to (slightly below) the
        # C++ driver's last loss, far below the initial loss
        assert nxt < rows[0][loss.name]
        assert abs(nxt - rows[-1][loss.name]) < 0.2
