"""Sequence + RNN op tests (reference test_sequence_pool.py,
test_lstm_op.py, test_gru_op.py patterns, masked-padded representation)."""
import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.layers.sequence import SEQ_LEN_SUFFIX


def _run_single_op(op_type, inputs, attrs, out_slots):
    prog = fluid.Program()
    block = prog.global_block
    in_names = {}
    feed = {}
    for slot, arr in inputs.items():
        name = slot.lower()
        block.create_var(name=name, shape=arr.shape, dtype=str(arr.dtype),
                         is_data=True)
        feed[name] = arr
        in_names[slot] = [name]
    out_names = {s: [s.lower() + "_out"] for s in out_slots}
    for s in out_slots:
        block.create_var(name=s.lower() + "_out")
    block.append_op(op_type, in_names, out_names, attrs)
    exe = fluid.Executor()
    return exe.run(prog, feed=feed,
                   fetch_list=[out_names[s][0] for s in out_slots])


def test_sequence_pool_modes():
    x = np.random.rand(3, 5, 4).astype("float32")
    lens = np.array([5, 2, 4], dtype="int32")
    mask = (np.arange(5)[None, :] < lens[:, None])[..., None]
    for mode, ref in [
        ("SUM", (x * mask).sum(1)),
        ("AVERAGE", (x * mask).sum(1) / lens[:, None]),
        ("MAX", np.where(mask, x, -np.inf).max(1)),
        ("FIRST", x[:, 0]),
        ("LAST", x[np.arange(3), lens - 1]),
    ]:
        out, _ = _run_single_op(
            "sequence_pool", {"X": x, "SeqLen": lens},
            {"pooltype": mode}, ["Out", "MaxIndex"])
        np.testing.assert_allclose(out, ref, rtol=1e-5,
                                   err_msg=f"mode {mode}")


def test_sequence_softmax_masks_padding():
    x = np.random.rand(2, 6).astype("float32")
    lens = np.array([4, 6], dtype="int32")
    (out,) = _run_single_op("sequence_softmax",
                            {"X": x, "SeqLen": lens}, {}, ["Out"])
    assert np.allclose(out[0, 4:], 0.0)
    np.testing.assert_allclose(out.sum(axis=1), [1.0, 1.0], rtol=1e-5)


def test_sequence_reverse():
    x = np.arange(12, dtype="float32").reshape(2, 6)
    lens = np.array([3, 6], dtype="int32")
    (out,) = _run_single_op("sequence_reverse",
                            {"X": x, "SeqLen": lens}, {}, ["Y"])
    np.testing.assert_allclose(out[0, :3], x[0, :3][::-1])
    np.testing.assert_allclose(out[0, 3:], x[0, 3:])
    np.testing.assert_allclose(out[1], x[1][::-1])


def test_lstm_op_shapes_and_length_masking():
    b, t, h = 2, 5, 8
    x = np.random.rand(b, t, 4 * h).astype("float32") * 0.1
    w = np.random.rand(h, 4 * h).astype("float32") * 0.1
    bias = np.random.rand(1, 4 * h).astype("float32") * 0.1
    lens = np.array([3, 5], dtype="int32")
    hidden, cell = _run_single_op(
        "lstm", {"Input": x, "Weight": w, "Bias": bias, "SeqLen": lens},
        {"use_peepholes": False}, ["Hidden", "Cell"])
    assert hidden.shape == (b, t, h)
    # state frozen past the sequence end for row 0
    np.testing.assert_allclose(hidden[0, 2], hidden[0, 3], rtol=1e-6)
    np.testing.assert_allclose(hidden[0, 3], hidden[0, 4], rtol=1e-6)
    assert not np.allclose(hidden[1, 3], hidden[1, 4])


def test_gru_op_matches_manual_step():
    b, t, h = 2, 3, 4
    x = np.random.rand(b, t, 3 * h).astype("float32") * 0.2
    w = np.random.rand(h, 3 * h).astype("float32") * 0.2
    (hidden,) = _run_single_op(
        "gru", {"Input": x, "Weight": w},
        {"origin_mode": False}, ["Hidden"])
    # manual first step from h=0
    xu, xr, xc = np.split(x[:, 0], 3, axis=-1)
    u = 1 / (1 + np.exp(-xu))
    cand = np.tanh(xc)
    h1 = u * cand
    np.testing.assert_allclose(hidden[:, 0], h1, rtol=1e-4)


def test_dynamic_lstm_trains():
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        words = fluid.layers.data("w", shape=[-1, 6], dtype="float32",
                                  append_batch_size=False)
        words.shape = (-1, 8, 6)
        label = fluid.layers.data("label", shape=[1], dtype="int64")
        proj = fluid.layers.fc(words, 16 * 4, num_flatten_dims=2)
        fluid.layers.sequence.bind_seq_len(proj, words)
        h, c = fluid.layers.dynamic_lstm(proj, 16 * 4,
                                         use_peepholes=False)
        last = fluid.layers.sequence_pool(h, "last")
        logits = fluid.layers.fc(last, 2)
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, label))
        fluid.optimizer.Adam(0.01).minimize(loss)
    exe = fluid.Executor()
    exe.run(startup)
    rng = np.random.RandomState(0)
    xs = rng.rand(16, 8, 6).astype("float32")
    lens = np.full((16,), 8, dtype="int32")
    ys = (xs[:, 0, 0] > 0.5).astype("int64")[:, None]
    losses = []
    for _ in range(30):
        out = exe.run(main, feed={"w": xs, "w" + SEQ_LEN_SUFFIX: lens,
                                  "label": ys}, fetch_list=[loss])
        losses.append(float(np.asarray(out[0]).reshape(-1)[0]))
    assert losses[-1] < losses[0], (losses[0], losses[-1])


def test_attention_op_causal():
    q = np.random.rand(2, 2, 4, 8).astype("float32")
    out, = _run_single_op("attention", {"Q": q, "K": q, "V": q},
                          {"causal": True, "scale": 0.5,
                           "dropout_rate": 0.0}, ["Out"])
    # first position attends only to itself -> output == v[:, :, 0]
    np.testing.assert_allclose(out[:, :, 0], q[:, :, 0], rtol=1e-5)


def test_transformer_tiny_trains():
    from paddle_tpu.models import transformer as T

    main, startup, cost = T.build_program(
        seq_len=8, d_model=32, n_heads=2, n_layers=1, d_inner=64,
        vocab=50, dropout_rate=0.0, with_optimizer=False)
    with fluid.program_guard(main, startup):
        fluid.optimizer.Adam(0.01).minimize(cost)
    exe = fluid.Executor()
    exe.run(startup)
    rng = np.random.RandomState(0)
    src = rng.randint(0, 50, (4, 8)).astype("int64")
    losses = []
    for _ in range(15):
        out = exe.run(main, feed={"src_ids": src, "tgt_ids": src,
                                  "label": src}, fetch_list=[cost])
        losses.append(float(np.asarray(out[0]).reshape(-1)[0]))
    assert losses[-1] < losses[0]


from op_test import OpTest


class TestCudnnLSTM(OpTest):
    """cudnn_lstm with the canonical packed weight layout vs a numpy
     2-layer LSTM oracle (reference cudnn_lstm_op.cc)."""

    def setUp(self):
        super().setUp()
        self.op_type = "cudnn_lstm"
        t, b, isz, h, layers = 3, 2, 4, 5, 2
        r = np.random.RandomState(0)
        x = (r.randn(t, b, isz) * 0.3).astype("float32")
        h0 = (r.randn(layers, b, h) * 0.3).astype("float32")
        c0 = (r.randn(layers, b, h) * 0.3).astype("float32")
        mats, flat = [], []
        for l in range(layers):
            i_l = isz if l == 0 else h
            wx = (r.randn(4 * h, i_l) * 0.3).astype("float32")
            wh = (r.randn(4 * h, h) * 0.3).astype("float32")
            mats.append((wx, wh))
            flat += [wx.ravel(), wh.ravel()]
        bias = []
        for l in range(layers):
            bx = (r.randn(4 * h) * 0.3).astype("float32")
            bh = (r.randn(4 * h) * 0.3).astype("float32")
            bias.append(bx + bh)
            flat += [bx, bh]
        w = np.concatenate(flat)

        def sig(v):
            return 1 / (1 + np.exp(-v))

        seq = x
        last_h = np.zeros((layers, b, h), np.float32)
        last_c = np.zeros((layers, b, h), np.float32)
        for l in range(layers):
            wx, wh = mats[l]
            hs = np.zeros((t, b, h), np.float32)
            hp, cp = h0[l].copy(), c0[l].copy()
            for step in range(t):
                g = seq[step] @ wx.T + hp @ wh.T + bias[l]
                gi, gf, gc, go = np.split(g, 4, axis=1)
                cp = sig(gf) * cp + sig(gi) * np.tanh(gc)
                hp = sig(go) * np.tanh(cp)
                hs[step] = hp
            last_h[l], last_c[l] = hp, cp
            seq = hs
        self.inputs = {"Input": x, "W": w, "InitH": h0, "InitC": c0}
        self.attrs = {"hidden_size": h, "input_size": isz,
                      "num_layers": layers, "is_test": True}
        self.outputs = {"Out": seq, "last_h": last_h,
                        "last_c": last_c}

    def test_output(self):
        self.check_output(atol=1e-5)

    def test_grad(self):
        self.check_grad(["Input", "W"], "Out",
                        no_grad_set={"InitH", "InitC"})


class TestCudnnLSTMBidirec(OpTest):
    """is_bidirec=True vs a numpy oracle: per layer a forward and a
    time-reversed LSTM over the same input, hidden states concatenated
    (cuDNN CUDNN_BIDIRECTIONAL pseudo-layer packing, direction minor —
    reference cudnn_lstm_op.cc / cudnn_rnn_cache.h)."""

    def setUp(self):
        super().setUp()
        self.op_type = "cudnn_lstm"
        t, b, isz, h, layers, dirs = 3, 2, 4, 5, 2, 2
        r = np.random.RandomState(1)
        x = (r.randn(t, b, isz) * 0.3).astype("float32")
        h0 = (r.randn(layers * dirs, b, h) * 0.3).astype("float32")
        c0 = (r.randn(layers * dirs, b, h) * 0.3).astype("float32")
        mats, flat = [], []
        for pl in range(layers * dirs):
            i_l = isz if pl // dirs == 0 else h * dirs
            wx = (r.randn(4 * h, i_l) * 0.3).astype("float32")
            wh = (r.randn(4 * h, h) * 0.3).astype("float32")
            mats.append((wx, wh))
            flat += [wx.ravel(), wh.ravel()]
        bias = []
        for pl in range(layers * dirs):
            bx = (r.randn(4 * h) * 0.3).astype("float32")
            bh = (r.randn(4 * h) * 0.3).astype("float32")
            bias.append(bx + bh)
            flat += [bx, bh]
        w = np.concatenate(flat)

        def sig(v):
            return 1 / (1 + np.exp(-v))

        def run_dir(seq, pl, reverse):
            wx, wh = mats[pl]
            order = range(t - 1, -1, -1) if reverse else range(t)
            hs = np.zeros((t, b, h), np.float32)
            hp, cp = h0[pl].copy(), c0[pl].copy()
            for step in order:
                g = seq[step] @ wx.T + hp @ wh.T + bias[pl]
                gi, gf, gc, go = np.split(g, 4, axis=1)
                cp = sig(gf) * cp + sig(gi) * np.tanh(gc)
                hp = sig(go) * np.tanh(cp)
                hs[step] = hp
            return hs, hp, cp

        seq = x
        last_h = np.zeros((layers * dirs, b, h), np.float32)
        last_c = np.zeros((layers * dirs, b, h), np.float32)
        for l in range(layers):
            outs = []
            for d in range(dirs):
                pl = l * dirs + d
                hs, hT, cT = run_dir(seq, pl, reverse=(d == 1))
                outs.append(hs)
                last_h[pl], last_c[pl] = hT, cT
            seq = np.concatenate(outs, axis=-1)
        self.inputs = {"Input": x, "W": w, "InitH": h0, "InitC": c0}
        self.attrs = {"hidden_size": h, "input_size": isz,
                      "num_layers": layers, "is_bidirec": True,
                      "is_test": True}
        self.outputs = {"Out": seq, "last_h": last_h,
                        "last_c": last_c}

    def test_output(self):
        self.check_output(atol=1e-5)

    def test_grad(self):
        self.check_grad(["Input", "W"], "Out",
                        no_grad_set={"InitH", "InitC"})


def test_layers_lstm_bidirec_trains():
    """layers.lstm(is_bidirec=True): output widens to 2H and a stacked
    bidirectional model trains (loss falls) — the reference lstm layer
    wraps the bidirectional cuDNN descriptor (layers/nn.py lstm)."""
    import paddle_tpu as fluid

    B, T, D, H = 4, 6, 8, 5
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[T, D], dtype="float32")
        y = fluid.layers.data(name="y", shape=[1], dtype="float32")
        out, h_last, c_last = fluid.layers.lstm(
            x, None, None, T, H, num_layers=2, is_bidirec=True)
        assert out.shape[-1] == 2 * H
        pooled = fluid.layers.reduce_mean(out, dim=[1, 2], keep_dim=False)
        pred = fluid.layers.fc(fluid.layers.reshape(pooled, [-1, 1]), 1)
        loss = fluid.layers.mean(fluid.layers.square(pred - y))
        fluid.optimizer.AdamOptimizer(0.02).minimize(loss)
    exe = fluid.Executor(fluid.TPUPlace(0))
    exe.run(startup)
    r = np.random.RandomState(0)
    xb = r.randn(B, T, D).astype(np.float32)
    yb = xb.sum(axis=(1, 2), keepdims=False).reshape(B, 1).astype(
        np.float32) * 0.1
    lens = np.full((B,), T, np.int32)
    losses = [float(np.mean(exe.run(
        main, feed={"x": xb, "y": yb, "x@SEQ_LEN": lens},
        fetch_list=[loss])[0]))
        for _ in range(25)]
    assert losses[-1] < losses[0] * 0.7, (losses[0], losses[-1])
