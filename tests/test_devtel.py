"""Device-side flight data (observability/devtel.py + costmodel.py +
the decode-engine/serving integration).

What must hold:

* **counter units** — ticks count device While iterations (not
  scheduler cycles), the occupancy integral sums live lanes per tick,
  admission counters count REAL lanes per tier, and the burst exit
  reason is one-hot per burst — all deterministic with no-EOS prompts
  (end_id outside the vocab: argmax can never emit it, so every lane
  runs to buffer exhaustion);
* **window semantics** — ``stats()['device_telemetry']`` re-bases on
  ``reset=True`` exactly like the r14 speculative counters;
* **golden keysets** — the ``paddle_tpu_devtel_*`` metric names and
  the stats keyset are a published contract;
* **zero steady-state compiles / executable bound with telemetry
  enabled** — the counters ride state_in/state_out of the SAME serve
  executables, so enabling observability must not change the
  compile story;
* **flight-recorder interior** — a forced slow burst (lone request
  outgrowing a tiny paged pool) retains an incident whose span tree
  carries exit reason, tick count, occupancy integral, and the
  expected-vs-actual cost annotation (observability/costmodel.py);
* **cost model units** — snapshot capture, lazy probe gating on
  FLAGS_observability, and the median-rate calibration arithmetic.

Determinism: the scheduler tests drive the server SINGLE-THREADED
(start=False + manual cycles — the test_paged_decode discipline) so
burst boundaries and admission order are exact, not race-lucky.
"""
import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import unique_name
from paddle_tpu.core.scope import Scope
from paddle_tpu.flags import FLAGS, set_flags
from paddle_tpu.inference.serving import (
    ContinuousGenerationServer, PagedContinuousGenerationServer)
from paddle_tpu.models.decode_engine import (BlockPoolExhausted,
                                             CacheConfig)
from paddle_tpu.observability import costmodel as obs_costmodel
from paddle_tpu.observability import devtel
from paddle_tpu.observability import metrics as obs_metrics

V, D, L, S, MAXT = 16, 32, 1, 8, 16
NO_EOS = V + 7   # argmax over [0, V) can never emit it: every lane
#                  decodes to buffer exhaustion, deterministically

DENSE_STATS_KEYS = {"ticks", "occupancy_integral", "exit_n_steps",
                    "exit_all_idle", "exit_min_active",
                    "admitted_miss", "mean_live_lanes"}
PAGED_STATS_KEYS = DENSE_STATS_KEYS | {
    "admitted_hit", "admitted_radix", "cow_blocks", "blocks_hwm",
    "prompt_entries_hwm", "pause_events", "preemptions"}
DENSE_METRICS = {
    "paddle_tpu_devtel_ticks_total",
    "paddle_tpu_devtel_occupancy_integral_total",
    "paddle_tpu_devtel_exit_n_steps_total",
    "paddle_tpu_devtel_exit_all_idle_total",
    "paddle_tpu_devtel_exit_min_active_total",
    "paddle_tpu_devtel_admit_miss_total",
}
PAGED_METRICS = DENSE_METRICS | {
    "paddle_tpu_devtel_admit_hit_total",
    "paddle_tpu_devtel_admit_radix_total",
    "paddle_tpu_devtel_cow_blocks_total",
    "paddle_tpu_devtel_blocks_hwm",
    "paddle_tpu_devtel_prompt_entries_hwm",
    "paddle_tpu_devtel_pause_events_total",
    "paddle_tpu_devtel_preemptions_total",
}
# chunked-prefill bundles (CacheConfig(chunk_tokens=C)) carry two more
# counters; plain paged bundles keep EXACTLY the set above
CHUNKED_STATS_KEYS = PAGED_STATS_KEYS | {
    "prefill_chunks", "prefill_occupancy_integral"}
CHUNKED_METRICS = PAGED_METRICS | {
    "paddle_tpu_devtel_prefill_chunks_total",
    "paddle_tpu_devtel_prefill_occupancy_integral_total",
}


@pytest.fixture(scope="module")
def ctx():
    """Initialized (NOT trained) weights + a dense bundle: devtel
    counts structure, not token quality, and no-EOS prompts make
    every lane's lifetime exactly maxT-1 ticks regardless of what
    garbage the untrained argmax emits."""
    from paddle_tpu.models import transformer as T

    scope = Scope()
    with unique_name.guard():
        main, startup, _ = T.build_program(
            seq_len=S, d_model=D, n_heads=2, n_layers=L, d_inner=64,
            vocab=V, with_optimizer=False, dropout_rate=0.0)
    exe = fluid.Executor(fluid.TPUPlace(0))
    exe.run(startup, scope=scope)
    kw = dict(seq_len=S, max_out_len=MAXT, d_model=D, n_heads=2,
              n_layers=L, d_inner=64, vocab=V, start_id=2,
              end_id=NO_EOS)
    with unique_name.guard():
        bundle = T.build_decode_step_program(n_slots=2,
                                             admit_buckets=[1], **kw)
    return {"exe": exe, "scope": scope, "bundle": bundle, "kw": kw}


@pytest.fixture
def obs(request):
    """Set an observability level for one test; restore + clear the
    process-global sinks afterwards so trace/flight/cost state never
    leaks across tests."""
    import paddle_tpu.observability as observability

    prev = FLAGS.observability

    def setter(level):
        set_flags({"FLAGS_observability": level})

    yield setter
    set_flags({"FLAGS_observability": prev})
    observability.reset()
    obs_costmodel.MODEL.reset()


def _prompts(n, rng=None):
    rng = rng or np.random.RandomState(0)
    return [rng.randint(3, V, (1, S)).astype(np.int64)
            for _ in range(n)]


def _drive(srv, max_cycles=200, until=None):
    """Single-threaded scheduler drive (the _loop body, minus the
    thread): deterministic burst boundaries."""
    for _ in range(max_cycles):
        if until is not None and until():
            return
        failures = []
        with srv._cv:
            if not srv._queue and all(l is None for l in srv._lanes):
                return
            admits = srv._plan_admissions_locked(failures)
            drain = not srv._queue
            n, m, run = srv._plan_burst_locked(admits, drain,
                                               failures)
        srv._fail_requests(failures)
        if run:
            srv._cycle(admits, n, m)
    raise AssertionError("scheduler did not converge")


def _dense(ctx, **kw):
    kw.setdefault("executor", ctx["exe"])
    kw.setdefault("scope", ctx["scope"])
    kw.setdefault("start", False)
    return ContinuousGenerationServer(ctx["bundle"], **kw)


def _paged_bundle(ctx, prefix, n_blocks=3, n_entries=2,
                  admit_buckets=(1, 2)):
    from paddle_tpu.models import transformer as T

    with unique_name.guard():
        return T.build_decode_step_program(
            n_slots=2, admit_buckets=list(admit_buckets),
            state_prefix=prefix,
            cache=CacheConfig(layout="paged", block_size=4,
                              n_blocks=n_blocks,
                              n_prompt_entries=n_entries),
            **ctx["kw"])


class TestCounterUnits:
    def test_single_request_ticks_and_occupancy_exact(self, ctx):
        srv = _dense(ctx)
        r = srv.submit(_prompts(1)[0])
        _drive(srv)
        dt = srv.stats()["device_telemetry"]
        toks = r.result(0)
        assert toks is not None
        # a no-EOS lane lives exactly maxT-1 ticks (room exhaustion),
        # alone in the pool -> occupancy integral == ticks
        assert dt["ticks"] == MAXT - 1
        assert dt["occupancy_integral"] == MAXT - 1
        assert dt["mean_live_lanes"] == 1.0
        assert dt["admitted_miss"] == 1
        # one drain burst, exited because the pool went idle
        assert dt["exit_all_idle"] == 1
        assert dt["exit_n_steps"] == 0
        srv.close()

    def test_exit_reason_mix_under_queue_pressure(self, ctx):
        # n_slots=2, admit_buckets=[1]: one admission per cycle keeps
        # the queue non-empty, so bursts cap at steps_per_tick and
        # exit n_steps until lanes start dying
        srv = _dense(ctx, steps_per_tick=4)
        for p in _prompts(3):
            srv.submit(p)
        _drive(srv)
        dt = srv.stats()["device_telemetry"]
        assert dt["admitted_miss"] == 3
        assert dt["exit_n_steps"] >= 1
        assert dt["exit_all_idle"] >= 1
        # every burst classified exactly once
        bursts = (dt["exit_n_steps"] + dt["exit_all_idle"]
                  + dt["exit_min_active"])
        assert dt["ticks"] >= bursts  # >= 1 tick per classified burst
        # total device work: 3 no-EOS lanes x (maxT-1) lane-ticks
        assert dt["occupancy_integral"] == 3 * (MAXT - 1)
        srv.close()

    def test_min_active_exit_fires_on_retirement(self, ctx):
        # exit_on_retire hands control back the moment a lane dies
        # while others live: staggered admissions (one per cycle)
        # guarantee lanes die on different ticks
        srv = _dense(ctx, steps_per_tick=4, exit_on_retire=True)
        for p in _prompts(3):
            srv.submit(p)
        _drive(srv)
        dt = srv.stats()["device_telemetry"]
        assert dt["exit_min_active"] >= 1
        srv.close()

    def test_reset_rebases_window(self, ctx):
        srv = _dense(ctx)
        srv.submit(_prompts(1)[0])
        _drive(srv)
        before = srv.stats(reset=True)["device_telemetry"]
        assert before["ticks"] == MAXT - 1
        after = srv.stats()["device_telemetry"]
        assert after["ticks"] == 0
        assert after["occupancy_integral"] == 0
        assert after["admitted_miss"] == 0
        assert after["mean_live_lanes"] is None
        # the metric samples stay CUMULATIVE (Prometheus convention)
        samples = dict(((name, labels.get("server")), v)
                       for name, labels, v
                       in srv._metrics_samples()
                       if name.startswith("paddle_tpu_devtel"))
        assert samples[("paddle_tpu_devtel_ticks_total",
                        srv._obs_id)] == MAXT - 1
        srv.close()

    def test_whole_loop_decode_steps_probe(self, ctx):
        """The unified tick-counter convention's whole-loop half: the
        fixed-name @decode_steps var (declared through
        devtel.declare_decode_steps) is fetchable and reports the
        early-exit iteration count."""
        from paddle_tpu.models import transformer as T
        from paddle_tpu.models.decode_engine import DECODE_STEPS_VAR

        assert DECODE_STEPS_VAR == devtel.DECODE_STEPS_VAR
        with unique_name.guard():
            m, _, _, buf = T.build_incremental_decode_program(
                **ctx["kw"])
        src = np.concatenate(_prompts(2), axis=0)
        toks, steps = ctx["exe"].run(
            m, feed={"src_ids": src},
            fetch_list=[buf, DECODE_STEPS_VAR], scope=ctx["scope"])
        assert int(np.asarray(steps).reshape(-1)[0]) == MAXT - 1


class TestPagedTelemetry:
    def test_hit_admissions_count_separately(self, ctx, obs):
        bundle = _paged_bundle(ctx, "@dtlp/", n_blocks=6)
        # radix_reuse=False: this test pins the HIT tier's counter —
        # under the default, an identical repeat prompt admits through
        # the radix tier instead (tel_admit_radix; ISSUE 17
        # cross-request reuse) and never reaches the hit program
        srv = PagedContinuousGenerationServer(
            bundle, executor=ctx["exe"], scope=ctx["scope"],
            start=False, radix_reuse=False)
        p = _prompts(1)[0]
        srv.submit(p)
        _drive(srv)
        srv.submit(p.copy())   # identical prompt: prefix HIT
        _drive(srv)
        dt = srv.stats()["device_telemetry"]
        assert dt["admitted_miss"] == 1
        assert dt["admitted_hit"] == 1
        assert dt["blocks_hwm"] >= 1
        assert dt["prompt_entries_hwm"] >= 1
        srv.close()

    def test_pause_and_preempt_surface_in_window(self, ctx):
        # two STAGGERED no-EOS lanes (one admission per cycle) over 4
        # blocks: the younger lane hits a block boundary the older
        # one already drained the free list for (one PAUSE), then
        # both block and the youngest is recompute-PREEMPTED — the
        # r13 dynamics, now visible in the telemetry window
        bundle = _paged_bundle(ctx, "@dtlq/", n_blocks=4,
                               admit_buckets=(1,))
        srv = PagedContinuousGenerationServer(
            bundle, executor=ctx["exe"], scope=ctx["scope"],
            start=False, steps_per_tick=4)
        rs = [srv.submit(p) for p in _prompts(2)]
        _drive(srv, max_cycles=400)
        for r in rs:
            assert r.result(0).shape == (MAXT,)
        dt = srv.stats()["device_telemetry"]
        assert dt["pause_events"] >= 1
        assert dt["preemptions"] >= 1
        assert 2 <= dt["blocks_hwm"] <= 4
        # window reset re-bases the host supplement too (hwm drops to
        # the CURRENT residency, not zero-forever)
        srv.stats(reset=True)
        dt2 = srv.stats()["device_telemetry"]
        assert dt2["pause_events"] == 0
        assert dt2["preemptions"] == 0
        srv.close()


class TestGoldenKeysets:
    def test_dense_stats_keyset(self, ctx):
        srv = _dense(ctx)
        srv.submit(_prompts(1)[0])
        _drive(srv)
        assert set(srv.stats()["device_telemetry"]) == DENSE_STATS_KEYS
        srv.close()

    def test_paged_stats_keyset(self, ctx):
        bundle = _paged_bundle(ctx, "@dtlk/", n_blocks=6)
        srv = PagedContinuousGenerationServer(
            bundle, executor=ctx["exe"], scope=ctx["scope"],
            start=False)
        srv.submit(_prompts(1)[0])
        _drive(srv)
        assert set(srv.stats()["device_telemetry"]) == PAGED_STATS_KEYS
        srv.close()

    def test_metric_names_exposed(self, ctx, obs):
        obs("metrics")
        bundle = _paged_bundle(ctx, "@dtlm/", n_blocks=6)
        srv = PagedContinuousGenerationServer(
            bundle, executor=ctx["exe"], scope=ctx["scope"],
            start=False)
        srv.submit(_prompts(1)[0])
        _drive(srv)
        names = {line.split("{")[0]
                 for line in obs_metrics.expose().splitlines()
                 if line.startswith("paddle_tpu_devtel")}
        assert PAGED_METRICS <= names
        srv.close()

    def test_registry_is_the_single_naming_source(self):
        # every metric name/stat key asserted above comes from the
        # declarative registry — the golden sets and the registry
        # must agree or the contract forked
        dense_logical = {c.stat for c in devtel.bundle_counters(False)}
        assert dense_logical | {"mean_live_lanes"} == DENSE_STATS_KEYS
        paged = {c.stat
                 for c in devtel.bundle_counters(True, chunked=False)} \
            | {c.stat for c in devtel.HOST_COUNTERS}
        assert paged | {"mean_live_lanes"} == PAGED_STATS_KEYS
        chunked = {c.stat for c in devtel.bundle_counters(True)} \
            | {c.stat for c in devtel.HOST_COUNTERS}
        assert chunked | {"mean_live_lanes"} == CHUNKED_STATS_KEYS
        assert {c.metric for c in devtel.BUNDLE_COUNTERS} \
            | {c.metric for c in devtel.HOST_COUNTERS} \
            == CHUNKED_METRICS


class TestChurnWithTelemetry:
    def test_zero_steady_state_compiles_and_executable_bound(
            self, ctx, obs):
        """The acceptance bound: telemetry enabled changes NOTHING
        about the compile story — the counters ride state_in/out of
        the same executables."""
        obs("metrics")
        exe = ctx["exe"]
        srv = _dense(ctx, steps_per_tick=4)
        warmed = srv._warmed_compiles
        assert warmed <= len(ctx["bundle"].serves)
        after_warm = exe.compile_count
        rng = np.random.RandomState(3)
        rs = [srv.submit(p) for p in _prompts(30, rng)]
        _drive(srv, max_cycles=600)
        for r in rs:
            assert r.result(0).shape == (MAXT,)
        assert exe.compile_count == after_warm, \
            "telemetry-on churn compiled something"
        dt = srv.stats()["device_telemetry"]
        assert dt["admitted_miss"] == 30
        assert dt["occupancy_integral"] == 30 * (MAXT - 1)
        srv.close()


class TestFlightRecorderInterior:
    def test_exhaustion_incident_carries_burst_interior(self, ctx,
                                                        obs):
        """The forced slow burst: a lone no-EOS request outgrows a
        2-block pool — pause-free growth, then hard exhaustion. The
        retained incident's span tree must explain the burst
        interior: exit reason, tick count, occupancy integral, and
        the expected-vs-actual cost annotation."""
        import paddle_tpu.observability as observability

        obs("trace")
        observability.reset()
        bundle = _paged_bundle(ctx, "@dtlx/", n_blocks=2)
        srv = PagedContinuousGenerationServer(
            bundle, executor=ctx["exe"], scope=ctx["scope"],
            start=False, steps_per_tick=2, drain_steps=2)
        r = srv.submit(_prompts(1)[0])
        _drive(srv, max_cycles=50,
               until=lambda: r.done())
        with pytest.raises(BlockPoolExhausted):
            r.result(0)
        report = observability.incident_report()
        assert report["incidents_retained"] >= 1
        inc = report["incidents"][-1]
        assert inc["status"] == "error"
        bursts = [s for s in inc["spans"]
                  if s["name"] == "slotpool.dispatch"
                  and "attrs" in s and "ticks" in s["attrs"]]
        assert bursts, inc["spans"]
        # 2-block coverage = 8 positions, 2-tick bursts: the doomed
        # request decodes st 0->8 in 4 bursts before exhaustion
        assert len(bursts) == 4
        for b in bursts:
            a = b["attrs"]
            assert a["ticks"] == 2
            assert a["occupancy_integral"] == 2  # lone lane
            assert a["exit_reason"] == "n_steps"
            assert a["actual_tick_ms"] > 0
        # calibration exists from burst 2 on (burst 1 admits; its
        # sample is prologue-corrected via the key snapshot):
        # expected-vs-actual
        annotated = [b for b in bursts
                     if "expected_tick_ms" in b["attrs"]]
        assert annotated and len(annotated) >= len(bursts) - 1
        for b in annotated:
            assert b["attrs"]["expected_tick_ms"] > 0
            assert b["attrs"]["tick_time_ratio"] > 0
        # the queue span carries the prefix tier (r13) so the whole
        # slow-admission story reads from one timeline
        queue = [s for s in inc["spans"]
                 if s["name"] == "slotpool.queue"]
        assert queue and queue[0]["attrs"]["prefix"] == "miss"
        srv.close()


class TestCostModel:
    def test_snapshot_fields_contract(self):
        fields = obs_costmodel.snapshot_fields()
        assert "flops" in fields and "bytes_accessed" in fields \
            and "kind" in fields and "fingerprint" in fields

    def test_lazy_probe_gated_on_flag(self, ctx, obs):
        """At off, a pending probe stays pending (lookup None); the
        first metrics-on lookup resolves it with ONE lowering."""
        obs("off")
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            from paddle_tpu import layers

            x = layers.data("x", shape=[4], dtype="float32")
            y = layers.fc(x, 8)
        exe = fluid.Executor(fluid.TPUPlace(0))
        scope = Scope()
        exe.run(startup, scope=scope)
        exe.run(main, feed={"x": np.ones((2, 4), "float32")},
                fetch_list=[y], scope=scope)
        obs_costmodel.MODEL.probe_resolutions = 0
        assert obs_costmodel.lookup(main) is None
        obs("metrics")
        snap = obs_costmodel.lookup(main)
        assert snap is not None and snap["flops"] > 0
        assert snap["kind"] == "block"
        assert obs_costmodel.MODEL.probe_resolutions == 1
        # second lookup is a dict read, not a second lowering
        assert obs_costmodel.lookup(main) is snap
        assert obs_costmodel.MODEL.probe_resolutions == 1

    def test_calibration_median_and_expected(self, obs):
        m = obs_costmodel.ExecutableCostModel()
        # 3x throttle swings straddle the median
        m.observe(1e6, 1.0)    # 1 Mflop/s
        m.observe(1e6, 3.0)    # throttled leg
        m.observe(3e6, 1.0)    # lucky leg
        assert m.flops_per_s() == pytest.approx(1e6)
        assert m.expected_ms(2e6) == pytest.approx(2000.0)
        assert m.expected_ms(None) is None
        assert obs_costmodel.ExecutableCostModel().expected_ms(1e6) \
            is None  # no calibration yet
