"""Sharded checkpointing + checkpoint-notify parity.

Reference: io.py:263 _save_distributed_persistables,
distribute_transpiler.py:1457 _create_checkpoint_save_block,
distributed_ops/checkpoint_notify_op.cc; SURVEY §5 orbax-style sharded
save with mesh-change restore.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import paddle_tpu as fluid
from paddle_tpu.parallel.checkpoint import (load_manifest, load_sharded,
                                            save_sharded)


def _mesh(shape, names):
    devs = np.array(jax.devices()[:int(np.prod(shape))]).reshape(shape)
    return Mesh(devs, names)


class TestShardedSaveLoad:
    def test_roundtrip_same_mesh(self, tmp_path):
        mesh = _mesh((8,), ("dp",))
        x = jnp.arange(64, dtype=jnp.float32).reshape(8, 8)
        xs = jax.device_put(x, NamedSharding(mesh, P("dp", None)))
        save_sharded(str(tmp_path), {"w": xs})
        # 8 disjoint shards, one per device
        m = load_manifest(str(tmp_path))
        assert len(m["w"]["shards"]) == 8
        out = load_sharded(str(tmp_path))
        np.testing.assert_array_equal(out["w"], np.asarray(x))

    def test_replicated_saves_once(self, tmp_path):
        mesh = _mesh((8,), ("dp",))
        x = jnp.ones((4, 4), jnp.float32)
        xs = jax.device_put(x, NamedSharding(mesh, P()))
        save_sharded(str(tmp_path), {"b": xs})
        m = load_manifest(str(tmp_path))
        assert len(m["b"]["shards"]) == 1  # replica_id 0 only

    def test_mesh_change_on_restore(self, tmp_path):
        # save sharded over 8-way dp, restore onto a 2x4 dp x tp mesh
        # with a DIFFERENT partitioning
        mesh8 = _mesh((8,), ("dp",))
        x = np.random.RandomState(0).randn(16, 8).astype(np.float32)
        xs = jax.device_put(jnp.asarray(x),
                            NamedSharding(mesh8, P("dp", None)))
        save_sharded(str(tmp_path), {"w": xs})

        mesh24 = _mesh((2, 4), ("dp", "tp"))
        target = NamedSharding(mesh24, P("dp", "tp"))
        out = load_sharded(str(tmp_path), shardings={"w": target})
        got = out["w"]
        assert got.sharding == target
        np.testing.assert_allclose(np.asarray(got), x)

    def test_program_level_roundtrip_with_mesh_change(self, tmp_path):
        # train a program, save sharded, restore into a fresh scope
        # with a replicated sharding over a different mesh
        rng = np.random.RandomState(1)
        xs = rng.randn(32, 8).astype(np.float32)
        ys = rng.randn(32, 1).astype(np.float32)
        prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(prog, startup):
            x = fluid.layers.data(name="x", shape=[8], dtype="float32")
            y = fluid.layers.data(name="y", shape=[1],
                                  dtype="float32")
            pred = fluid.layers.fc(x, size=1,
                                   param_attr=fluid.ParamAttr(
                                       name="w_ck"))
            loss = fluid.layers.mean(
                fluid.layers.square_error_cost(pred, y))
            fluid.optimizer.SGD(0.1).minimize(loss)
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.Scope()
        exe.run(startup, scope=scope)
        for _ in range(3):
            exe.run(prog, feed={"x": xs, "y": ys}, fetch_list=[loss],
                    scope=scope)
        w_trained = np.asarray(scope._get("w_ck")).copy()
        import paddle_tpu.core.scope as scope_mod

        old = scope_mod._global_scope
        scope_mod._global_scope = scope
        try:
            fluid.save_sharded_persistables(exe, str(tmp_path), prog)
        finally:
            scope_mod._global_scope = old

        scope2 = fluid.Scope()
        mesh = _mesh((4,), ("dp",))
        repl = NamedSharding(mesh, P())
        scope_mod._global_scope = scope2
        try:
            names = fluid.load_sharded_persistables(
                exe, str(tmp_path), prog, shardings=repl)
        finally:
            scope_mod._global_scope = old
        assert "w_ck" in names
        got = scope2._get("w_ck")
        np.testing.assert_allclose(np.asarray(got), w_trained,
                                   rtol=1e-6)
        assert got.sharding == repl


class TestCheckpointNotify:
    def test_pserver_table_shards_saved(self, tmp_path):
        from paddle_tpu.transpiler.pserver_runtime import (
            get_endpoint, reset_endpoints)

        reset_endpoints()
        eps = ["127.0.0.1:6174", "127.0.0.1:6175"]
        for i, ep in enumerate(eps):
            rt = get_endpoint(ep)
            rt.push_init(f"table.block{i}",
                         np.full((4, 2), float(i), np.float32))
            rt.push_init("unrelated", np.zeros((1,), np.float32))

        prog = fluid.Program()
        prog.global_block.append_op(
            "checkpoint_notify", {}, {},
            {"epmap": eps, "dir": str(tmp_path),
             "lookup_table": "table"})
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(prog)

        import os

        files = sorted(os.listdir(str(tmp_path / "__lookup_table__")))
        assert len(files) == 2  # one shard per endpoint; no unrelated
        assert all(f.startswith("table.block") for f in files)
        a = np.load(str(tmp_path / "__lookup_table__" / files[0]))
        np.testing.assert_array_equal(a, np.zeros((4, 2)))
        reset_endpoints()

    def test_save_persistables_routes_distributed(self, tmp_path):
        # a program tagged with a distributed table triggers the
        # notify path from the public save_persistables API
        from paddle_tpu.transpiler.pserver_runtime import (
            get_endpoint, reset_endpoints)

        reset_endpoints()
        ep = "127.0.0.1:6176"
        get_endpoint(ep).push_init("emb.block0",
                                   np.ones((2, 2), np.float32))
        prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(prog, startup):
            x = fluid.layers.data(name="x", shape=[4],
                                  dtype="float32")
            fluid.layers.fc(x, size=2,
                            param_attr=fluid.ParamAttr(name="w_loc"))
        prog._distributed_lookup_table = "emb"
        prog._pserver_endpoints = [ep]
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.Scope()
        exe.run(startup, scope=scope)
        import paddle_tpu.core.scope as scope_mod

        old = scope_mod._global_scope
        scope_mod._global_scope = scope
        try:
            fluid.save_persistables(exe, str(tmp_path), prog)
        finally:
            scope_mod._global_scope = old
        import os

        assert os.path.exists(str(tmp_path / "w_loc"))  # local var
        table_dir = tmp_path / "__lookup_table__"
        assert any(f.startswith("emb.block0")
                   for f in os.listdir(str(table_dir)))
        reset_endpoints()
