"""Sharded checkpointing + checkpoint-notify parity.

Reference: io.py:263 _save_distributed_persistables,
distribute_transpiler.py:1457 _create_checkpoint_save_block,
distributed_ops/checkpoint_notify_op.cc; SURVEY §5 orbax-style sharded
save with mesh-change restore.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import paddle_tpu as fluid
from paddle_tpu.parallel.checkpoint import (load_manifest, load_sharded,
                                            save_sharded)


def _mesh(shape, names):
    devs = np.array(jax.devices()[:int(np.prod(shape))]).reshape(shape)
    return Mesh(devs, names)


class TestShardedSaveLoad:
    def test_roundtrip_same_mesh(self, tmp_path):
        mesh = _mesh((8,), ("dp",))
        x = jnp.arange(64, dtype=jnp.float32).reshape(8, 8)
        xs = jax.device_put(x, NamedSharding(mesh, P("dp", None)))
        save_sharded(str(tmp_path), {"w": xs})
        # 8 disjoint shards, one per device
        m = load_manifest(str(tmp_path))
        assert len(m["w"]["shards"]) == 8
        out = load_sharded(str(tmp_path))
        np.testing.assert_array_equal(out["w"], np.asarray(x))

    def test_replicated_saves_once(self, tmp_path):
        mesh = _mesh((8,), ("dp",))
        x = jnp.ones((4, 4), jnp.float32)
        xs = jax.device_put(x, NamedSharding(mesh, P()))
        save_sharded(str(tmp_path), {"b": xs})
        m = load_manifest(str(tmp_path))
        assert len(m["b"]["shards"]) == 1  # replica_id 0 only

    def test_mesh_change_on_restore(self, tmp_path):
        # save sharded over 8-way dp, restore onto a 2x4 dp x tp mesh
        # with a DIFFERENT partitioning
        mesh8 = _mesh((8,), ("dp",))
        x = np.random.RandomState(0).randn(16, 8).astype(np.float32)
        xs = jax.device_put(jnp.asarray(x),
                            NamedSharding(mesh8, P("dp", None)))
        save_sharded(str(tmp_path), {"w": xs})

        mesh24 = _mesh((2, 4), ("dp", "tp"))
        target = NamedSharding(mesh24, P("dp", "tp"))
        out = load_sharded(str(tmp_path), shardings={"w": target})
        got = out["w"]
        assert got.sharding == target
        np.testing.assert_allclose(np.asarray(got), x)

    def test_program_level_roundtrip_with_mesh_change(self, tmp_path):
        # train a program, save sharded, restore into a fresh scope
        # with a replicated sharding over a different mesh
        rng = np.random.RandomState(1)
        xs = rng.randn(32, 8).astype(np.float32)
        ys = rng.randn(32, 1).astype(np.float32)
        prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(prog, startup):
            x = fluid.layers.data(name="x", shape=[8], dtype="float32")
            y = fluid.layers.data(name="y", shape=[1],
                                  dtype="float32")
            pred = fluid.layers.fc(x, size=1,
                                   param_attr=fluid.ParamAttr(
                                       name="w_ck"))
            loss = fluid.layers.mean(
                fluid.layers.square_error_cost(pred, y))
            fluid.optimizer.SGD(0.1).minimize(loss)
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.Scope()
        exe.run(startup, scope=scope)
        for _ in range(3):
            exe.run(prog, feed={"x": xs, "y": ys}, fetch_list=[loss],
                    scope=scope)
        w_trained = np.asarray(scope._get("w_ck")).copy()
        import paddle_tpu.core.scope as scope_mod

        old = scope_mod._global_scope
        scope_mod._global_scope = scope
        try:
            fluid.save_sharded_persistables(exe, str(tmp_path), prog)
        finally:
            scope_mod._global_scope = old

        scope2 = fluid.Scope()
        mesh = _mesh((4,), ("dp",))
        repl = NamedSharding(mesh, P())
        scope_mod._global_scope = scope2
        try:
            names = fluid.load_sharded_persistables(
                exe, str(tmp_path), prog, shardings=repl)
        finally:
            scope_mod._global_scope = old
        assert "w_ck" in names
        got = scope2._get("w_ck")
        np.testing.assert_allclose(np.asarray(got), w_trained,
                                   rtol=1e-6)
        assert got.sharding == repl


class TestCheckpointNotify:
    def test_pserver_table_shards_saved(self, tmp_path):
        from paddle_tpu.transpiler.pserver_runtime import (
            get_endpoint, reset_endpoints)

        reset_endpoints()
        eps = ["127.0.0.1:6174", "127.0.0.1:6175"]
        for i, ep in enumerate(eps):
            rt = get_endpoint(ep)
            rt.push_init(f"table.block{i}",
                         np.full((4, 2), float(i), np.float32))
            rt.push_init("unrelated", np.zeros((1,), np.float32))

        prog = fluid.Program()
        prog.global_block.append_op(
            "checkpoint_notify", {}, {},
            {"epmap": eps, "dir": str(tmp_path),
             "lookup_table": "table"})
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(prog)

        import os

        files = sorted(os.listdir(str(tmp_path / "__lookup_table__")))
        assert len(files) == 2  # one shard per endpoint; no unrelated
        assert all(f.startswith("table.block") for f in files)
        a = np.load(str(tmp_path / "__lookup_table__" / files[0]))
        np.testing.assert_array_equal(a, np.zeros((4, 2)))
        reset_endpoints()

    def test_save_persistables_routes_distributed(self, tmp_path):
        # a program tagged with a distributed table triggers the
        # notify path from the public save_persistables API
        from paddle_tpu.transpiler.pserver_runtime import (
            get_endpoint, reset_endpoints)

        reset_endpoints()
        ep = "127.0.0.1:6176"
        get_endpoint(ep).push_init("emb.block0",
                                   np.ones((2, 2), np.float32))
        prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(prog, startup):
            x = fluid.layers.data(name="x", shape=[4],
                                  dtype="float32")
            fluid.layers.fc(x, size=2,
                            param_attr=fluid.ParamAttr(name="w_loc"))
        prog._distributed_lookup_table = "emb"
        prog._pserver_endpoints = [ep]
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.Scope()
        exe.run(startup, scope=scope)
        import paddle_tpu.core.scope as scope_mod

        old = scope_mod._global_scope
        scope_mod._global_scope = scope
        try:
            fluid.save_persistables(exe, str(tmp_path), prog)
        finally:
            scope_mod._global_scope = old
        import os

        assert os.path.exists(str(tmp_path / "w_loc"))  # local var
        table_dir = tmp_path / "__lookup_table__"
        assert any(f.startswith("emb.block0")
                   for f in os.listdir(str(table_dir)))
        reset_endpoints()


def test_train_checkpoint_crash_resume(tmp_path):
    """TrainCheckpoint: save/prune/atomic-marker + crash-resume
    continuing the exact trajectory (beyond-reference capability,
    SURVEY §5 failure detection)."""
    import os

    import paddle_tpu as fluid
    from paddle_tpu import unique_name

    def build():
        fluid._reset_global_scope()
        unique_name.switch()
        fluid.seed(11)
        prog = fluid.Program()
        startup = fluid.Program()
        with fluid.program_guard(prog, startup):
            x = fluid.layers.data("x", shape=(6,), dtype="float32")
            y = fluid.layers.data("y", shape=(1,), dtype="float32")
            pred = fluid.layers.fc(x, size=1)
            loss = fluid.layers.mean(
                fluid.layers.square_error_cost(pred, y))
            fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
        return prog, startup, loss

    rng = np.random.RandomState(0)
    feed = {"x": rng.rand(8, 6).astype("float32"),
            "y": rng.rand(8, 1).astype("float32")}
    d = str(tmp_path / "ck")

    # uninterrupted run: 8 steps, checkpoint every 2
    prog, startup, loss = build()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    ck = fluid.TrainCheckpoint(d, exe, prog, max_to_keep=2)
    assert ck.resume() == 0
    ref = []
    for step in range(8):
        out = exe.run(prog, feed=feed, fetch_list=[loss.name])
        ref.append(float(np.asarray(out[0])))
        if step % 2 == 1:
            ck.save(step)
    # retention: only max_to_keep step dirs remain
    kept = [n for n in os.listdir(d) if n.startswith("step_")]
    assert len(kept) == 2, kept
    assert ck.latest_step() == 7

    # "crash" after step 5's checkpoint: fresh process resumes at 6
    prog2, startup2, loss2 = build()
    exe2 = fluid.Executor(fluid.CPUPlace())
    exe2.run(startup2)
    ck2 = fluid.TrainCheckpoint(d, exe2, prog2, max_to_keep=2)
    # simulate the crash point by resuming from step 5's checkpoint
    import shutil
    shutil.rmtree(os.path.join(d, "step_7"))
    import json
    with open(os.path.join(d, "LATEST"), "w") as f:
        json.dump({"step": 5}, f)
    start = ck2.resume()
    assert start == 6
    got = []
    for step in range(start, 8):
        out = exe2.run(prog2, feed=feed, fetch_list=[loss2.name])
        got.append(float(np.asarray(out[0])))
    np.testing.assert_allclose(got, ref[6:], atol=1e-6, rtol=1e-6)


def test_train_checkpoint_marker_fallback_and_orphans(tmp_path):
    """Corrupt/stale LATEST falls back to the newest surviving step
    dir; orphaned staging dirs are swept at init."""
    import json
    import os

    import paddle_tpu as fluid
    from paddle_tpu import unique_name

    fluid._reset_global_scope()
    unique_name.switch()
    prog = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(prog, startup):
        x = fluid.layers.data("x", shape=(3,), dtype="float32")
        fluid.layers.fc(x, size=1)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    d = str(tmp_path / "ck2")
    ck = fluid.TrainCheckpoint(d, exe, prog, max_to_keep=3)
    ck.save(1)
    ck.save(3)
    # stale marker pointing at a deleted dir -> fall back to step 3
    with open(os.path.join(d, "LATEST"), "w") as f:
        json.dump({"step": 9}, f)
    assert ck.latest_step() == 3
    # truncated marker (power loss) -> fallback, not a crash
    with open(os.path.join(d, "LATEST"), "w") as f:
        f.write("")
    assert ck.latest_step() == 3
    assert ck.resume() == 4
    # re-save of the marker step must never leave a dead marker target
    ck.save(3)
    assert ck.latest_step() == 3
    # orphan staging dirs are swept by a fresh instance
    os.makedirs(os.path.join(d, ".ck_tmp_orphan"), exist_ok=True)
    fluid.TrainCheckpoint(d, exe, prog)
    assert not any(n.startswith(".ck_") for n in os.listdir(d))
