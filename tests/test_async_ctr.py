"""AsyncExecutor + MultiSlotDataFeed + distributed lookup table tests.

Parity model: reference unittests/test_async_executor.py (file-driven
multithread training), data_feed tests, and the distributed-lookup-
table path of test_dist_transpiler.py / dist_ctr.py.
"""
import os

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.async_executor import AsyncExecutor
from paddle_tpu.data_feed import DataFeedDesc, MultiSlotDataFeed
from paddle_tpu.transpiler import (DistributeTranspiler,
                                   DistributeTranspilerConfig,
                                   pserver_runtime)


def _write_ctr_files(tmpdir, n_files=2, lines_per_file=64, seed=7):
    """MultiSlot text files: dnn_data (sparse), lr_data (sparse),
    click (dense label). Class-correlated ids so training converges."""
    rng = np.random.RandomState(seed)
    files = []
    for fi in range(n_files):
        path = os.path.join(str(tmpdir), f"ctr_{fi}.txt")
        with open(path, "w") as f:
            for _ in range(lines_per_file):
                click = int(rng.randint(0, 2))
                lo = 0 if click == 0 else 500
                n1 = int(rng.randint(1, 6))
                dnn = rng.randint(lo, lo + 500, n1)
                n2 = int(rng.randint(1, 4))
                lr = rng.randint(lo, lo + 500, n2)
                line = (f"{n1} " + " ".join(map(str, dnn)) + " "
                        f"{n2} " + " ".join(map(str, lr)) + " "
                        f"1 {click}")
                f.write(line + "\n")
        files.append(path)
    return files


def _ctr_desc(batch_size=16):
    desc = DataFeedDesc()
    desc.set_batch_size(batch_size)
    desc.add_slot("dnn_data", type="uint64")
    desc.add_slot("lr_data", type="uint64")
    desc.add_slot("click", type="uint64", is_dense=True)
    return desc


class TestMultiSlotDataFeed:
    def test_parse_and_batch(self, tmp_path):
        files = _write_ctr_files(tmp_path, n_files=1, lines_per_file=10)
        feed = MultiSlotDataFeed(_ctr_desc(4))
        batches = list(feed.read_batches(files[0]))
        assert len(batches) == 3  # 4+4+2
        b = batches[0]
        assert b["dnn_data"].dtype == np.int64
        assert b["dnn_data"].ndim == 2 and b["dnn_data"].shape[0] == 4
        assert b["click"].shape == (4, 1)

    def test_parse_error_clear(self, tmp_path):
        p = os.path.join(str(tmp_path), "bad.txt")
        with open(p, "w") as f:
            f.write("3 1 2\n")  # declares 3 values, provides 2
        feed = MultiSlotDataFeed(_ctr_desc(2))
        with pytest.raises(ValueError, match="declares 3 values"):
            list(feed.read_batches(p))

    def test_desc_roundtrip(self):
        desc = _ctr_desc(8)
        import json

        blob = json.loads(desc.desc())
        assert blob["batch_size"] == 8
        assert [s["name"] for s in blob["slots"]] == [
            "dnn_data", "lr_data", "click"]


class TestAsyncExecutor:
    def _build_ctr(self):
        from paddle_tpu.models import ctr

        dnn = fluid.layers.data("dnn_data", shape=[-1], dtype="int64",
                                append_batch_size=False)
        dnn.shape = (-1, -1)
        lr = fluid.layers.data("lr_data", shape=[-1], dtype="int64",
                               append_batch_size=False)
        lr.shape = (-1, -1)
        click = fluid.layers.data("click", shape=[1], dtype="int64")
        loss, acc, auc_var, _ = ctr.ctr_dnn_model(
            dnn, lr, click, dnn_dict_dim=1001, lr_dict_dim=1001)
        fluid.optimizer.AdamOptimizer(
            learning_rate=0.05).minimize(loss)
        return loss

    def test_run_from_files_trains(self, tmp_path):
        loss = self._build_ctr()
        exe = fluid.Executor(fluid.TPUPlace(0))
        exe.run(fluid.default_startup_program())
        files = _write_ctr_files(tmp_path, n_files=6,
                                 lines_per_file=96)
        async_exe = AsyncExecutor(fluid.TPUPlace(0))
        hist = async_exe.run(fluid.default_main_program(),
                             _ctr_desc(16), files, thread_num=2,
                             fetch=[loss])
        vals = hist[loss.name]
        assert len(vals) == 36  # 6 files * 6 batches
        assert np.mean(vals[-8:]) < np.mean(vals[:8]) - 0.02

    def test_empty_filelist_raises(self):
        with pytest.raises(ValueError):
            AsyncExecutor().run(fluid.default_main_program(),
                                _ctr_desc(), [], thread_num=2)


class TestDistributedLookupTable:
    EPS = ["127.0.0.1:8101", "127.0.0.1:8102"]

    def _build(self, vocab=40, dim=4):
        ids = fluid.layers.data("ids", shape=[5], dtype="int64")
        y = fluid.layers.data("y", shape=[1], dtype="float32")
        emb = fluid.layers.embedding(ids, size=[vocab, dim],
                                     is_distributed=True)
        pooled = fluid.layers.reduce_sum(emb, dim=1)
        pred = fluid.layers.fc(input=pooled, size=1)
        loss = fluid.layers.mean(
            fluid.layers.square_error_cost(pred, y))
        fluid.optimizer.SGDOptimizer(learning_rate=0.01).minimize(loss)
        return ids, y, loss

    def _transpile(self, trainers=1):
        cfg = DistributeTranspilerConfig()
        cfg.slice_var_up = False
        t = DistributeTranspiler(cfg)
        t.transpile(0, pservers=",".join(self.EPS), trainers=trainers)
        for ep in self.EPS:
            pserver_runtime.configure_endpoint(
                ep, t.get_pserver_program(ep), num_trainers=trainers,
                sync_mode=True)
        return t

    def test_table_rewritten_and_sharded(self):
        self._build()
        pserver_runtime.reset_endpoints()
        t = self._transpile()
        types = [o.type for o in
                 t.get_trainer_program().global_block.ops]
        assert "prefetch" in types and "prefetch_grad" in types
        assert "lookup_table" not in types
        exe = fluid.Executor(fluid.TPUPlace(0))
        exe.run(t.get_startup_program())
        s0 = pserver_runtime.get_endpoint(self.EPS[0]).store
        s1 = pserver_runtime.get_endpoint(self.EPS[1]).store
        shard_keys0 = [k for k in s0 if ".shard" in k]
        shard_keys1 = [k for k in s1 if ".shard" in k]
        assert shard_keys0 and shard_keys1
        # shards hold the mod-sharded rows of the initial table
        w0 = np.asarray(fluid.global_scope()._get(
            shard_keys0[0].split(".shard")[0]))
        np.testing.assert_allclose(
            np.asarray(s0[shard_keys0[0]]), w0[0::2], rtol=1e-6)
        np.testing.assert_allclose(
            np.asarray(s1[shard_keys1[0]]), w0[1::2], rtol=1e-6)

    def test_prefetch_forward_parity(self):
        ids, y, loss = self._build()
        pserver_runtime.reset_endpoints()
        t = self._transpile()
        exe = fluid.Executor(fluid.TPUPlace(0))
        exe.run(t.get_startup_program())
        w = np.array(np.asarray(fluid.global_scope()._get(
            [n for n in fluid.global_scope().local_var_names()
             if "emb" in n or "w" in n.lower()][0])))
        # forward through prefetch must equal a local gather
        table_name = [n for n, i in t._dist_tables.items()][0]
        w = np.array(np.asarray(fluid.global_scope()._get(table_name)))
        idv = np.array([[0, 1, 2, 3, 5], [7, 8, 9, 10, 11]], np.int64)
        emb_out = next(o for o in
                       t.get_trainer_program().global_block.ops
                       if o.type == "prefetch").output("Out")[0]
        got, = exe.run(t.get_trainer_program(),
                       feed={"ids": idv,
                             "y": np.zeros((2, 1), np.float32)},
                       fetch_list=[emb_out])
        np.testing.assert_allclose(got, w[idv], rtol=1e-5, atol=1e-6)

    def test_adam_table_rejected(self):
        ids = fluid.layers.data("ids", shape=[5], dtype="int64")
        y = fluid.layers.data("y", shape=[1], dtype="float32")
        emb = fluid.layers.embedding(ids, size=[40, 4],
                                     is_distributed=True)
        pred = fluid.layers.fc(
            input=fluid.layers.reduce_sum(emb, dim=1), size=1)
        loss = fluid.layers.mean(
            fluid.layers.square_error_cost(pred, y))
        fluid.optimizer.AdamOptimizer(
            learning_rate=0.01).minimize(loss)
        cfg = DistributeTranspilerConfig()
        with pytest.raises(ValueError, match="SGD only"):
            DistributeTranspiler(cfg).transpile(
                0, pservers=",".join(self.EPS), trainers=1)

    def test_padding_idx_zeroes_and_protects_row(self):
        pserver_runtime.reset_endpoints()
        ids = fluid.layers.data("ids", shape=[4], dtype="int64")
        y = fluid.layers.data("y", shape=[1], dtype="float32")
        emb = fluid.layers.embedding(ids, size=[40, 4],
                                     is_distributed=True,
                                     padding_idx=0)
        pred = fluid.layers.fc(
            input=fluid.layers.reduce_sum(emb, dim=1), size=1)
        loss = fluid.layers.mean(
            fluid.layers.square_error_cost(pred, y))
        fluid.optimizer.SGDOptimizer(learning_rate=0.05).minimize(loss)
        t = self._transpile()
        exe = fluid.Executor(fluid.TPUPlace(0))
        exe.run(t.get_startup_program())
        table = list(t._dist_tables)[0]
        info = t._dist_tables[table]
        rt0 = pserver_runtime.get_endpoint(self.EPS[0])
        row0_before = np.array(rt0.store[info["shards"][0]][0])
        emb_out = next(o for o in
                       t.get_trainer_program().global_block.ops
                       if o.type == "prefetch").output("Out")[0]
        idv = np.array([[0, 0, 3, 5]], np.int64)
        got, l = exe.run(
            t.get_trainer_program(),
            feed={"ids": idv, "y": np.ones((1, 1), np.float32)},
            fetch_list=[emb_out, loss.name])
        np.testing.assert_allclose(got[0, 0], np.zeros(4))  # pad = 0
        np.testing.assert_allclose(got[0, 1], np.zeros(4))
        assert np.abs(got[0, 2]).sum() > 0
        # pad row received no gradient
        row0_after = np.array(rt0.store[info["shards"][0]][0])
        np.testing.assert_allclose(row0_after, row0_before)

    def test_sparse_training_updates_only_touched_rows(self):
        ids, y, loss = self._build()
        pserver_runtime.reset_endpoints()
        t = self._transpile()
        exe = fluid.Executor(fluid.TPUPlace(0))
        exe.run(t.get_startup_program())
        table_name = list(t._dist_tables)[0]
        info = t._dist_tables[table_name]
        rt0 = pserver_runtime.get_endpoint(self.EPS[0])
        before0 = np.array(rt0.store[info["shards"][0]])
        idv = np.array([[2, 2, 4, 6, 8]], np.int64)  # even rows: ep0
        losses = []
        for _ in range(10):
            l, = exe.run(t.get_trainer_program(),
                         feed={"ids": idv,
                               "y": np.ones((1, 1), np.float32)},
                         fetch_list=[loss.name])
            losses.append(float(np.asarray(l)))
        after0 = np.array(rt0.store[info["shards"][0]])
        touched = np.array([1, 2, 3, 4])  # local rows = ids // 2
        untouched = np.array([0, 5, 6, 7])
        assert np.abs(after0[touched] - before0[touched]).sum() > 0
        np.testing.assert_allclose(after0[untouched],
                                   before0[untouched])
        # odd-row shard on ep1 untouched entirely
        rt1 = pserver_runtime.get_endpoint(self.EPS[1])
        assert losses[-1] < losses[0]


class TestRaggedFloatSlots:
    def test_variable_length_float_slot_padded(self, tmp_path):
        """ADVICE.md: sparse float slots with ragged lengths must pad
        like the int path (reference MultiSlotDataFeed supports
        variable-length float slots) instead of raising in np.stack."""
        path = os.path.join(str(tmp_path), "f.txt")
        with open(path, "w") as f:
            f.write("2 0.5 1.5\n3 1.0 2.0 3.0\n")
        desc = DataFeedDesc()
        desc.set_batch_size(2)
        desc.add_slot("fv", type="float")
        feed = MultiSlotDataFeed(desc)
        b = list(feed.read_batches(path))[0]
        assert b["fv"].dtype == np.float32
        assert b["fv"].shape == (2, 4)  # padded to pow2 bucket
        np.testing.assert_array_equal(b["fv@SEQ_LEN"], [2, 3])
        np.testing.assert_allclose(b["fv"][0, :2], [0.5, 1.5])
        assert b["fv"][0, 2:].sum() == 0


class TestDenseHeavyWarning:
    def test_dense_heavy_program_warns(self, tmp_path):
        """Round-1 review weak #4: the last-writer-wins dense caveat
        must be guarded, not just documented."""
        import warnings as W

        prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(prog, startup):
            x = fluid.layers.data(name="xd", shape=[64],
                                  dtype="float32")
            h = fluid.layers.fc(x, size=2048)  # dense-heavy
            loss = fluid.layers.mean(h)
            fluid.optimizer.SGD(0.1).minimize(loss)
        with W.catch_warnings(record=True) as rec:
            W.simplefilter("always")
            fluid.AsyncExecutor()._warn_if_dense_heavy(prog)
        assert any("dense-heavy" in str(w.message) for w in rec)

    def test_ctr_program_does_not_warn(self):
        import warnings as W

        from paddle_tpu.models import ctr as M

        prog, startup, cost, _ = M.build_program()
        with W.catch_warnings(record=True) as rec:
            W.simplefilter("always")
            fluid.AsyncExecutor()._warn_if_dense_heavy(prog)
        assert not any("dense-heavy" in str(w.message) for w in rec)
