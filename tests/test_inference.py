"""Inference engine tests.

Parity model: reference inference/api/api_impl_tester.cc,
analysis_predictor_tester.cc and the ir fuse-pass unit tests
(ir/fc_fuse_pass_tester.cc-style op-count assertions).
"""
import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import ir
from paddle_tpu.inference import (AnalysisConfig, AnalysisPredictor,
                                  PaddleTensor, create_paddle_predictor)


def _train_and_export(tmpdir, with_conv=False):
    """Small model trained a few steps then exported."""
    img = fluid.layers.data(name="img", shape=[784], dtype="float32")
    label = fluid.layers.data(name="label", shape=[1], dtype="int64")
    x = img
    if with_conv:
        x = fluid.layers.reshape(img, shape=[-1, 1, 28, 28])
        x = fluid.layers.conv2d(x, num_filters=4, filter_size=3,
                                padding=1)
        x = fluid.layers.batch_norm(x)
        x = fluid.layers.relu(x)
        x = fluid.layers.reshape(x, shape=[-1, 4 * 28 * 28])
    hidden = fluid.layers.fc(input=x, size=32, act="relu")
    hidden = fluid.layers.dropout(hidden, dropout_prob=0.3)
    out = fluid.layers.fc(input=hidden, size=10, act="softmax")
    loss = fluid.layers.mean(
        fluid.layers.cross_entropy(input=out, label=label))
    fluid.optimizer.SGDOptimizer(learning_rate=0.3).minimize(loss)

    exe = fluid.Executor(fluid.TPUPlace(0))
    exe.run(fluid.default_startup_program())
    feeder = fluid.DataFeeder(feed_list=[img, label])
    reader = fluid.batch(fluid.dataset.mnist.train(), batch_size=32)
    for i, b in enumerate(reader()):
        if i >= 25:
            break
        exe.run(feed=feeder.feed(b), fetch_list=[loss])
    fluid.save_inference_model(str(tmpdir), ["img"], [out], exe)
    test_b = next(fluid.batch(fluid.dataset.mnist.test(), 64)())
    xs = np.stack([s[0] for s in test_b])
    ys = np.array([s[1] for s in test_b])
    eval_prog = fluid.default_main_program().clone(
        for_test=True)._prune([out.name])
    ref, = exe.run(eval_prog, feed={"img": xs}, fetch_list=[out.name])
    return xs, ys, np.asarray(ref)


class TestAnalysisPredictor:
    def test_run_matches_training_forward(self, tmp_path):
        xs, ys, ref = _train_and_export(tmp_path)
        config = AnalysisConfig(str(tmp_path))
        pred = create_paddle_predictor(config)
        assert pred.get_input_names() == ["img"]
        outs = pred.run([PaddleTensor(xs, name="img")])
        np.testing.assert_allclose(outs[0].data, ref, rtol=2e-4,
                                   atol=2e-5)
        acc = (np.argmax(outs[0].data, 1) == ys).mean()
        assert acc > 0.5

    def test_zero_copy_api(self, tmp_path):
        xs, ys, ref = _train_and_export(tmp_path)
        pred = create_paddle_predictor(AnalysisConfig(str(tmp_path)))
        in_t = pred.get_input_tensor("img")
        in_t.copy_from_cpu(xs)
        pred.zero_copy_run()
        out_t = pred.get_output_tensor(pred.get_output_names()[0])
        np.testing.assert_allclose(out_t.copy_to_cpu(), ref, rtol=2e-4,
                                   atol=2e-5)

    def test_ir_optim_shrinks_program_same_output(self, tmp_path):
        xs, ys, ref = _train_and_export(tmp_path, with_conv=True)
        raw = AnalysisConfig(str(tmp_path))
        raw.switch_ir_optim(False)
        p_raw = create_paddle_predictor(raw)
        opt = AnalysisConfig(str(tmp_path))
        p_opt = create_paddle_predictor(opt)
        n_raw = len(p_raw.program().global_block.ops)
        n_opt = len(p_opt.program().global_block.ops)
        assert n_opt < n_raw  # bn folded, fc fused, dropout gone
        types = [o.type for o in p_opt.program().global_block.ops]
        assert "batch_norm" not in types
        assert "dropout" not in types
        assert "fc" in types
        o_raw = p_raw.run([PaddleTensor(xs, name="img")])[0].data
        o_opt = p_opt.run([PaddleTensor(xs, name="img")])[0].data
        np.testing.assert_allclose(o_opt, o_raw, rtol=1e-3, atol=1e-4)
        np.testing.assert_allclose(o_raw, ref, rtol=2e-4, atol=2e-5)

    def test_bf16_serving_close_to_f32(self, tmp_path):
        xs, ys, ref = _train_and_export(tmp_path)
        cfg = AnalysisConfig(str(tmp_path))
        cfg.enable_tpu_bf16()
        pred = create_paddle_predictor(cfg)
        out = pred.run([PaddleTensor(xs, name="img")])[0].data
        assert out.dtype == np.float32  # outputs upcast for the caller
        np.testing.assert_allclose(out, ref, rtol=0.1, atol=0.05)
        acc_ref = (np.argmax(ref, 1) == ys).mean()
        acc_bf16 = (np.argmax(out, 1) == ys).mean()
        assert abs(acc_ref - acc_bf16) < 0.1

    def test_clone_independent(self, tmp_path):
        xs, ys, ref = _train_and_export(tmp_path)
        pred = create_paddle_predictor(AnalysisConfig(str(tmp_path)))
        clone = pred.clone()
        o1 = pred.run([PaddleTensor(xs, name="img")])[0].data
        o2 = clone.run([PaddleTensor(xs, name="img")])[0].data
        np.testing.assert_allclose(o1, o2, rtol=1e-5)

    def test_missing_model_raises(self):
        with pytest.raises(ValueError):
            create_paddle_predictor(AnalysisConfig())

    def test_trt_refused(self):
        cfg = AnalysisConfig("/tmp/whatever")
        with pytest.raises(RuntimeError):
            cfg.enable_tensorrt_engine()


class TestIRPasses:
    def test_fc_fuse_pass_counts(self):
        x = fluid.layers.data(name="x", shape=[8], dtype="float32")
        h = fluid.layers.fc(input=x, size=4, act="relu")
        out = fluid.layers.fc(input=h, size=2)
        prog = fluid.default_main_program()
        before = [o.type for o in prog.global_block.ops]
        assert before.count("mul") == 2
        ir.apply_passes(prog, ["fc_fuse_pass"])
        after = [o.type for o in prog.global_block.ops]
        assert after.count("fc") == 2
        assert "mul" not in after and "elementwise_add" not in after
        assert "relu" not in after  # folded into first fc

    def test_fc_fuse_preserves_semantics(self):
        x = fluid.layers.data(name="x", shape=[8], dtype="float32")
        h = fluid.layers.fc(input=x, size=4, act="relu")
        out = fluid.layers.fc(input=h, size=2)
        prog = fluid.default_main_program()
        exe = fluid.Executor(fluid.TPUPlace(0))
        exe.run(fluid.default_startup_program())
        xs = np.random.RandomState(0).randn(3, 8).astype(np.float32)
        ref, = exe.run(prog, feed={"x": xs}, fetch_list=[out])
        fused = prog.clone(for_test=True)
        ir.apply_passes(fused, ["fc_fuse_pass"])
        got, = exe.run(fused, feed={"x": xs}, fetch_list=[out.name])
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)

    def test_fc_fuse_skips_residual_add(self):
        x = fluid.layers.data(name="x", shape=[8], dtype="float32")
        h = fluid.layers.fc(input=x, size=8, bias_attr=False)
        out = fluid.layers.elementwise_add(h, x)  # residual, not a bias
        prog = fluid.default_main_program()
        ir.apply_passes(prog, ["fc_fuse_pass"])
        types = [o.type for o in prog.global_block.ops]
        assert "elementwise_add" in types  # untouched
        assert "fc" not in types

    def test_fc_fuse_3d_keeps_rank(self):
        x = fluid.layers.data(name="x", shape=[5, 8], dtype="float32")
        out = fluid.layers.fc(input=x, size=4, num_flatten_dims=2)
        prog = fluid.default_main_program()
        exe = fluid.Executor(fluid.TPUPlace(0))
        exe.run(fluid.default_startup_program())
        xs = np.random.RandomState(0).randn(3, 5, 8).astype(np.float32)
        ref, = exe.run(prog, feed={"x": xs}, fetch_list=[out])
        assert ref.shape == (3, 5, 4)
        fused = prog.clone(for_test=True)
        ir.apply_passes(fused, ["fc_fuse_pass"])
        assert "fc" in [o.type for o in fused.global_block.ops]
        got, = exe.run(fused, feed={"x": xs}, fetch_list=[out.name])
        assert got.shape == (3, 5, 4)
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)

    def test_paddle_tensor_dtype_without_data(self):
        t = PaddleTensor(name="img", dtype=fluid.inference.PaddleDType
                         .FLOAT32)
        assert t.data is None and t.shape == []

    def test_unknown_pass_raises(self):
        x = fluid.layers.data(name="x", shape=[4], dtype="float32")
        with pytest.raises(KeyError):
            ir.apply_passes(fluid.default_main_program(), ["nope_pass"])

    def test_graph_structure(self):
        x = fluid.layers.data(name="x", shape=[4], dtype="float32")
        y = fluid.layers.relu(x)
        g = ir.Graph(fluid.default_main_program())
        assert any(n.is_op() and n.name == "relu" for n in g.op_nodes)
        relu_node = [n for n in g.op_nodes if n.name == "relu"][0]
        assert any(v.name == "x" for v in relu_node.inputs)


def test_clone_survives_export_dir_removal(tmp_path):
    """ADVICE.md: clone() must clone from the in-memory program (as the
    reference does), not re-read the export dir; and must not share the
    config's mutable pass list."""
    import shutil

    xs, ys, ref = _train_and_export(tmp_path)
    pred = create_paddle_predictor(AnalysisConfig(str(tmp_path)))
    shutil.rmtree(str(tmp_path))
    clone = pred.clone()
    o1 = pred.run([PaddleTensor(xs, name="img")])[0].data
    o2 = clone.run([PaddleTensor(xs, name="img")])[0].data
    np.testing.assert_allclose(o1, o2, rtol=1e-5)
    cfg_a, cfg_b = pred._config, clone._config
    cfg_b.append_pass("made_up_pass")
    assert "made_up_pass" not in cfg_a.all_passes()


def test_stablehlo_export_round_trip(tmp_path):
    """StableHLO serving export (SURVEY §5: the TPU-native
    save_inference_model artifact): exported program must reproduce
    the live predictor bit-for-bit at the exported shape, reject other
    shapes, and carry feed/fetch metadata."""
    import os

    import paddle_tpu as fluid
    from paddle_tpu import unique_name

    fluid._reset_global_scope()
    unique_name.switch()
    prog = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(prog, startup):
        img = fluid.layers.data("img", shape=(1, 8, 8),
                                dtype="float32")
        conv = fluid.layers.conv2d(img, num_filters=4, filter_size=3,
                                   padding=1, act="relu")
        pool = fluid.layers.pool2d(conv, pool_size=8, pool_type="avg")
        out = fluid.layers.fc(pool, size=3, act="softmax")
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    xv = np.random.RandomState(0).rand(2, 1, 8, 8).astype("float32")
    ref = np.asarray(exe.run(prog, feed={"img": xv},
                             fetch_list=[out.name])[0])

    mdir = str(tmp_path / "model")
    fluid.save_inference_model(mdir, ["img"],
                               [prog.global_block.var(out.name)], exe,
                               main_program=prog)
    sdir = str(tmp_path / "served")
    fluid.inference.export_stablehlo(mdir, {"img": xv}, sdir)
    assert sorted(os.listdir(sdir)) == ["meta.json", "model.stablehlo"]

    served = fluid.inference.load_stablehlo(sdir)
    assert served.feed_names == ["img"]
    got = served({"img": xv})[0]
    np.testing.assert_allclose(got, ref, atol=1e-5, rtol=1e-5)

    with pytest.raises(ValueError, match="shape-specialized"):
        served({"img": xv[:1]})
    with pytest.raises(ValueError, match="missing feed"):
        served({})


def test_stablehlo_train_step_export(tmp_path):
    """Train-step StableHLO artifact (reference C++ train demo
    capability, inference/train/demo): driving the frozen step from
    its saved initial state reproduces the live trajectory exactly."""
    import paddle_tpu as fluid
    from paddle_tpu import unique_name

    fluid._reset_global_scope()
    unique_name.switch()
    fluid.seed(3)
    prog = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(prog, startup):
        x = fluid.layers.data("x", shape=(6,), dtype="float32")
        y = fluid.layers.data("y", shape=(1,), dtype="float32")
        pred = fluid.layers.fc(x, size=1)
        loss = fluid.layers.mean(
            fluid.layers.square_error_cost(pred, y))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    r = np.random.RandomState(0)
    feed = {"x": r.rand(8, 6).astype("float32"),
            "y": r.rand(8, 1).astype("float32")}
    out = str(tmp_path / "train_art")
    fluid.inference.export_train_stablehlo(
        prog, fluid.global_scope(), feed, [loss.name], out)
    live = [float(np.asarray(exe.run(prog, feed=feed,
                                     fetch_list=[loss.name])[0]))
            for _ in range(5)]
    tr = fluid.inference.load_train_stablehlo(out)
    state = tr.initial_state()
    art = []
    for _ in range(5):
        state, fetches = tr.train_step(state, feed)
        art.append(float(fetches[0].reshape(-1)[0]))
    np.testing.assert_allclose(art, live, atol=1e-6, rtol=1e-6)
    assert art[-1] < art[0]


def test_stablehlo_train_step_with_dropout_rng(tmp_path):
    """The train artifact threads the PRNG key (state["__rng__"]):
    dropout draws fresh masks per step and the trajectory matches the
    live Executor seeded identically."""
    import paddle_tpu as fluid
    from paddle_tpu import unique_name

    fluid._reset_global_scope()
    unique_name.switch()
    fluid.seed(9)
    prog = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(prog, startup):
        x = fluid.layers.data("x", shape=(6,), dtype="float32")
        y = fluid.layers.data("y", shape=(1,), dtype="float32")
        h = fluid.layers.fc(x, size=16, act="relu")
        h = fluid.layers.dropout(
            h, 0.4, dropout_implementation="upscale_in_train")
        pred = fluid.layers.fc(h, size=1)
        loss = fluid.layers.mean(
            fluid.layers.square_error_cost(pred, y))
        fluid.optimizer.SGD(learning_rate=0.05).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    r = np.random.RandomState(1)
    feed = {"x": r.rand(8, 6).astype("float32"),
            "y": r.rand(8, 1).astype("float32")}
    out = str(tmp_path / "train_do")
    fluid.inference.export_train_stablehlo(
        prog, fluid.global_scope(), feed, [loss.name], out)
    live = [float(np.asarray(exe.run(prog, feed=feed,
                                     fetch_list=[loss.name])[0]))
            for _ in range(5)]
    tr = fluid.inference.load_train_stablehlo(out)
    state = tr.initial_state()
    art = []
    for _ in range(5):
        state, fetches = tr.train_step(state, feed)
        art.append(float(fetches[0].reshape(-1)[0]))
    np.testing.assert_allclose(art, live, atol=1e-6, rtol=1e-6)
    # fresh noise per step: consecutive losses are not locked to one
    # repeated mask trajectory (coarse check: steps differ)
    assert len({round(v, 8) for v in art}) == len(art)
    # kind validation both ways
    with pytest.raises(ValueError, match="train_step"):
        fluid.inference.load_stablehlo(out)
    with pytest.raises(TypeError, match="train_step artifact"):
        tr({"x": feed["x"]})
