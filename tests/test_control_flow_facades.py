"""StaticRNN / DynamicRNN / Switch / IfElse as real constructs
(reference layers/control_flow.py:266 StaticRNN, :1262 DynamicRNN,
:1126 Switch/IfElse; lowered to the recurrent/run_block_if/ifelse ops).

The snippets mirror reference user code: PTB-style DynamicRNN
(tests/unittests/test_dyn_rnn.py), piecewise-decay Switch
(learning_rate_scheduler.py piecewise_decay), IfElse batch split
(test_ifelse.py).
"""
import numpy as np
import pytest

import paddle_tpu as fluid


def _exe():
    return fluid.Executor(fluid.CPUPlace())


class TestStaticRNN:
    def test_accumulator_matches_numpy(self):
        # rnn accumulates x_t + m_{t-1}; time-major input [T, B, D]
        t, b, d = 5, 3, 4
        x_np = np.random.RandomState(0).randn(t, b, d).astype(
            np.float32)
        prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(prog, startup):
            x = fluid.layers.data(name="x", shape=[t, b, d],
                                  dtype="float32",
                                  append_batch_size=False)
            rnn = fluid.layers.StaticRNN()
            with rnn.step():
                xt = rnn.step_input(x)
                mem = rnn.memory(shape=[b, d], batch_ref=x,
                                 init_value=0.0, init_batch_dim_idx=0,
                                 ref_batch_dim_idx=1)
                acc = fluid.layers.elementwise_add(xt, mem)
                rnn.update_memory(mem, acc)
                rnn.step_output(acc)
            out = rnn()
        got, = _exe().run(prog, feed={"x": x_np}, fetch_list=[out])
        np.testing.assert_allclose(got, np.cumsum(x_np, axis=0),
                                   rtol=1e-5)

    def test_fc_rnn_trains(self):
        t, b, d, h = 4, 6, 5, 8
        rng = np.random.RandomState(1)
        x_np = rng.randn(t, b, d).astype(np.float32)
        y_np = rng.randn(b, 1).astype(np.float32)
        prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(prog, startup):
            x = fluid.layers.data(name="x", shape=[t, b, d],
                                  dtype="float32",
                                  append_batch_size=False)
            y = fluid.layers.data(name="y", shape=[b, 1],
                                  dtype="float32",
                                  append_batch_size=False)
            rnn = fluid.layers.StaticRNN()
            with rnn.step():
                xt = rnn.step_input(x)
                mem = rnn.memory(shape=[b, h], batch_ref=x,
                                 init_value=0.0)
                nxt = fluid.layers.fc([xt, mem], size=h, act="tanh")
                rnn.update_memory(mem, nxt)
                rnn.step_output(nxt)
            seq = rnn()  # [T, B, H]
            last = fluid.layers.slice(seq, axes=[0], starts=[t - 1],
                                      ends=[t])
            last = fluid.layers.reshape(last, shape=[b, h])
            pred = fluid.layers.fc(last, size=1)
            loss = fluid.layers.mean(
                fluid.layers.square_error_cost(pred, y))
            fluid.optimizer.SGD(0.05).minimize(loss)
        exe = _exe()
        exe.run(startup)
        ls = [float(np.asarray(exe.run(
            prog, feed={"x": x_np, "y": y_np},
            fetch_list=[loss])[0]).reshape(-1)[0]) for _ in range(20)]
        assert ls[-1] < ls[0] * 0.9


class TestDynamicRNN:
    def test_ptb_style_varlen_rnn(self):
        # reference test_dyn_rnn.py shape: embedded sentence ->
        # DynamicRNN fc step with memory -> last step state
        b, t, d, h = 4, 6, 5, 8
        rng = np.random.RandomState(2)
        x_np = rng.randn(b, t, d).astype(np.float32)
        lens = np.array([6, 3, 5, 1], np.int32)
        prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(prog, startup):
            sent = fluid.layers.data(name="sent", shape=[t, d],
                                     dtype="float32")
            drnn = fluid.layers.DynamicRNN()
            with drnn.block():
                word = drnn.step_input(sent)
                prev = drnn.memory(shape=[h], value=0.0)
                hidden = fluid.layers.fc([word, prev], size=h,
                                         act="relu")
                drnn.update_memory(prev, hidden)
                drnn.output(hidden)
            out = drnn()  # [B, T, H] + @SEQ_LEN
            last = fluid.layers.sequence_last_step(out)
        exe = _exe()
        exe.run(startup)
        o, l = exe.run(prog,
                       feed={"sent": x_np, "sent@SEQ_LEN": lens},
                       fetch_list=[out, last])
        assert o.shape == (b, t, h)
        # masked beyond length: zeros
        assert np.abs(o[1, 3:]).sum() == 0
        assert np.abs(o[3, 1:]).sum() == 0
        # last step = state at len-1
        np.testing.assert_allclose(l[1], o[1, 2], rtol=1e-6)
        np.testing.assert_allclose(l[0], o[0, 5], rtol=1e-6)

    def test_trains_binary_classifier(self):
        b, t, d, h = 8, 5, 4, 8
        rng = np.random.RandomState(3)
        x_np = rng.randn(b, t, d).astype(np.float32)
        y_np = (x_np.sum((1, 2)) > 0).astype(np.int64)[:, None]
        lens = np.full((b,), t, np.int32)
        prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(prog, startup):
            sent = fluid.layers.data(name="sent", shape=[t, d],
                                     dtype="float32")
            label = fluid.layers.data(name="y", shape=[1],
                                      dtype="int64")
            drnn = fluid.layers.DynamicRNN()
            with drnn.block():
                word = drnn.step_input(sent)
                prev = drnn.memory(shape=[h], value=0.0)
                hidden = fluid.layers.fc([word, prev], size=h,
                                         act="tanh")
                drnn.update_memory(prev, hidden)
                drnn.output(hidden)
            last = fluid.layers.sequence_last_step(drnn())
            logits = fluid.layers.fc(last, size=2)
            loss = fluid.layers.mean(
                fluid.layers.softmax_with_cross_entropy(logits, label))
            fluid.optimizer.SGD(0.5).minimize(loss)
        exe = _exe()
        exe.run(startup)
        feed = {"sent": x_np, "sent@SEQ_LEN": lens, "y": y_np}
        ls = [float(np.asarray(exe.run(prog, feed=feed,
                                       fetch_list=[loss])[0])
                    .reshape(-1)[0]) for _ in range(30)]
        assert ls[-1] < ls[0] * 0.5


class TestSwitch:
    def _piecewise(self, step_value):
        # the reference piecewise-decay snippet, run unchanged
        prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(prog, startup):
            step = fluid.layers.fill_constant([1], "float32",
                                              float(step_value))
            lr = fluid.layers.tensor.create_global_var(
                [1], 0.0, "float32", persistable=True, name="sw_lr")
            with fluid.layers.Switch() as switch:
                with switch.case(fluid.layers.less_than_value(
                        step, 100.0)):
                    fluid.layers.tensor.assign(
                        fluid.layers.fill_constant([1], "float32",
                                                   1.0), lr)
                with switch.case(fluid.layers.less_than_value(
                        step, 200.0)):
                    fluid.layers.tensor.assign(
                        fluid.layers.fill_constant([1], "float32",
                                                   0.5), lr)
                with switch.default():
                    fluid.layers.tensor.assign(
                        fluid.layers.fill_constant([1], "float32",
                                                   0.1), lr)
        exe = _exe()
        exe.run(startup)
        out, = exe.run(prog, fetch_list=[lr])
        return float(np.asarray(out).reshape(-1)[0])

    def test_first_true_case_wins(self):
        assert self._piecewise(50) == pytest.approx(1.0)
        assert self._piecewise(150) == pytest.approx(0.5)
        assert self._piecewise(500) == pytest.approx(0.1)


class TestIfElse:
    def test_rowwise_split_merge(self):
        # reference test_ifelse.py pattern: rows < 0 negated, rows >= 0
        # doubled, merged in order
        x_np = np.array([[-2.0], [3.0], [-1.0], [4.0]], np.float32)
        prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(prog, startup):
            x = fluid.layers.data(name="x", shape=[1],
                                  dtype="float32")
            zero = fluid.layers.fill_constant([1], "float32", 0.0)
            cond = fluid.layers.less_than(x, zero)
            ie = fluid.layers.IfElse(cond)
            with ie.true_block():
                d = ie.input(x)
                ie.output(fluid.layers.scale(d, scale=-1.0))
            with ie.false_block():
                d = ie.input(x)
                ie.output(fluid.layers.scale(d, scale=2.0))
            out = ie()[0]
        got, = _exe().run(prog, feed={"x": x_np}, fetch_list=[out])
        np.testing.assert_allclose(
            got, [[2.0], [6.0], [1.0], [8.0]], rtol=1e-6)


def test_dynamic_rnn_inner_weights_receive_grads():
    """Regression: the recurrent op must emit grads for its sub-block
    externals (weights INSIDE the rnn step) — previously they were
    silently frozen (differentiable=False)."""
    import numpy as np

    b, t, d, h = 4, 5, 6, 7
    prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(prog, startup):
        sent = fluid.layers.data(name="sent", shape=[t, d],
                                 dtype="float32")
        label = fluid.layers.data(name="y", shape=[1], dtype="int64")
        drnn = fluid.layers.DynamicRNN()
        with drnn.block():
            word = drnn.step_input(sent)
            prev = drnn.memory(shape=[h], value=0.0)
            hidden = fluid.layers.fc(
                [word, prev], size=h, act="tanh",
                param_attr=[fluid.ParamAttr(name="wx_reg"),
                            fluid.ParamAttr(name="wh_reg")])
            drnn.update_memory(prev, hidden)
            drnn.output(hidden)
        last = fluid.layers.sequence_last_step(drnn())
        logits = fluid.layers.fc(last, size=2)
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, label))
        fluid.optimizer.SGD(0.5).minimize(loss)
    assert any("wx_reg@GRAD" in op.output_arg_names
               for op in prog.global_block.ops)
    exe = _exe()
    exe.run(startup)
    scope = fluid.global_scope()
    w0 = np.array(scope._get("wx_reg"))
    rng = np.random.RandomState(0)
    feed = {"sent": rng.randn(b, t, d).astype(np.float32),
            "sent@SEQ_LEN": np.full((b,), t, np.int32),
            "y": rng.randint(0, 2, (b, 1)).astype(np.int64)}
    exe.run(prog, feed=feed, fetch_list=[loss])
    assert not np.allclose(w0, np.asarray(scope._get("wx_reg")))
