"""Chunked-prefill + radix-preemption CONTRACTS (ISSUE 17), fast
lane: everything here is host-side logic, static analysis, or a
white-box scheduler probe over one small L=1 bundle — the end-to-end
serve waves (token parity, latency, disaggregation) live in
tests/test_chunked_prefill.py and tests/test_disagg_serving.py (slow
lane).

* ``CacheConfig`` chunk validation: ``chunk_tokens == 1`` is rejected
  (single-query attention drifts ~1e-7 off the monolithic encoder —
  the bit-exact parity contract), chunking needs the paged layout,
  and the cache token carries ``("chunk", C)`` so a chunked and an
  unchunked build of one geometry never dedupe;
* ``PromptPrefixCache.invalidate`` typestate (the abandoned
  part-written-prefill path): pinned entries refuse, invalidated
  prompts stop matching (even as partials) and the slot is reusable;
* radix-aware preemption (white-box): under hard pool exhaustion the
  scheduler bulk-evicts refcount-1 radix leaves BEFORE preempting,
  and when it must preempt it picks the lane with the DEEPEST shared
  prefix (least exclusive work lost), youngest t_admit tiebreak;
* analysis contracts: the ``chunk_cursor`` ownership source is
  registered and the chunk phase programs discharge PTA180 (telemetry
  contract) and PTA190/191/192 (pool ownership) with zero errors.
"""
import concurrent.futures
import types

import pytest

import paddle_tpu as fluid
from paddle_tpu import unique_name
from paddle_tpu.analysis import ERROR, absint, run_checks
from paddle_tpu.core.scope import Scope
from paddle_tpu.inference.serving import PagedContinuousGenerationServer
from paddle_tpu.models import transformer as T
from paddle_tpu.models.decode_engine import (BlockLifetimeError,
                                             BlockPoolExhausted,
                                             CacheConfig,
                                             PromptPrefixCache)

V, D, H, L, S, MAXT = 16, 16, 2, 1, 8, 16
BS, NB, E, C = 4, 10, 2, 4
N_SLOTS = 4
NPH = 2 * L + 2


@pytest.fixture(scope="module")
def built():
    """One SMALL untrained chunked bundle: the contracts below probe
    scheduler/prover structure, never token quality, so the cheapest
    geometry that has a radix tier and chunk phases wins."""
    fluid.seed(0)
    scope = Scope()
    with unique_name.guard():
        _, t_st, _ = T.build_program(
            seq_len=S, d_model=D, n_heads=H, n_layers=L, d_inner=32,
            vocab=V, with_optimizer=False, dropout_rate=0.0)
    with unique_name.guard():
        bundle = T.build_decode_step_program(
            n_slots=N_SLOTS, admit_buckets=[1], state_prefix="@cc/",
            seq_len=S, max_out_len=MAXT, d_model=D, n_heads=H,
            n_layers=L, d_inner=32, vocab=V, start_id=2, end_id=1,
            cache=CacheConfig(layout="paged", block_size=BS,
                              n_blocks=NB, n_prompt_entries=E,
                              chunk_tokens=C))
    exe = fluid.Executor(fluid.TPUPlace(0))
    exe.run(t_st, scope=scope)
    return {"scope": scope, "exe": exe, "bundle": bundle}


class TestCacheConfigChunking:
    def _cfg(self, **kw):
        kw.setdefault("layout", "paged")
        kw.setdefault("block_size", BS)
        kw.setdefault("n_blocks", NB)
        kw.setdefault("n_prompt_entries", E)
        return CacheConfig(**kw)

    def test_single_token_chunks_rejected(self):
        # C == 1 lowers attention to a single-query contraction whose
        # accumulation order drifts off the monolithic encoder — the
        # bit-exact parity contract rejects it at validation
        with pytest.raises(ValueError, match="chunk_tokens == 1"):
            self._cfg(chunk_tokens=1).validate(MAXT)

    def test_negative_chunks_rejected(self):
        with pytest.raises(ValueError, match=">= 0"):
            self._cfg(chunk_tokens=-2).validate(MAXT)

    def test_chunking_needs_paged_layout(self):
        with pytest.raises(ValueError, match="paged layout"):
            CacheConfig(layout="dense", chunk_tokens=4).validate(MAXT)

    def test_token_carries_chunk_suffix(self):
        plain = self._cfg().token()
        chunked = self._cfg(chunk_tokens=C).token()
        # append-only: historical unchunked tokens stay byte-identical
        assert chunked[:len(plain)] == plain
        assert chunked[len(plain):] == ("chunk", C)

    def test_n_chunks_ceil(self):
        assert self._cfg(chunk_tokens=4).n_chunks(10) == 3
        assert self._cfg(chunk_tokens=5).n_chunks(10) == 2
        assert self._cfg(chunk_tokens=4).n_chunks(12) == 3
        assert self._cfg().n_chunks(10) == 0


class TestPromptEntryInvalidate:
    def test_invalidate_pinned_entry_raises(self):
        pc = PromptPrefixCache(2, C)
        e = pc.acquire_fresh((1, 2, 3, 4))
        with pytest.raises(BlockLifetimeError, match="invalidate"):
            pc.invalidate(e)

    def test_invalidate_forgets_prompt_and_recycles_slot(self):
        pc = PromptPrefixCache(2, C)
        prompt = (1, 2, 3, 4, 5)
        e = pc.acquire_fresh(prompt)
        pc.release(e)
        assert pc.lookup(prompt) == ("hit", e)
        pc.invalidate(e)
        # the abandoned part-written entry must never be looked up
        # again — not even as a partial (its head count is gone too)
        assert pc.lookup(prompt) == ("miss", None)
        assert pc.lookup(prompt[:C] + (9,)) == ("miss", None)
        assert pc.acquire_fresh((7, 7, 7, 7)) == e
        # idempotent on an already-forgotten entry
        pc.release(e)
        pc.invalidate(e)
        pc.invalidate(e)


class TestRadixAwarePreemption:
    """White-box: drive _plan_burst_locked directly on an idle
    (start=False) server with hand-built lane state and a drained
    block pool — the only way to pin the VICTIM CHOICE without
    racing a live scheduler into a specific exhaustion interleaving."""

    def _req(self, t_admit):
        return types.SimpleNamespace(
            t_admit=t_admit, t_first=None,
            reply=concurrent.futures.Future(), trace=None)

    def _idle(self, built):
        return PagedContinuousGenerationServer(
            built["bundle"], executor=built["exe"],
            scope=built["scope"], steps_per_tick=4, start=False)

    def _drain_pool(self, srv):
        held = []
        while True:
            b = srv._blocks.alloc()
            if b is None:
                return held
            held.append(b)

    def test_deepest_shared_lane_preempted_first(self, built):
        srv = self._idle(built)
        try:
            held = self._drain_pool(srv)
            freed = []
            srv._free_lane_locked = lambda slot: freed.append(slot)
            old_plain = self._req(t_admit=5.0)   # older, depth 0
            young_shared = self._req(t_admit=9.0)
            srv._lanes[0] = old_plain
            srv._lanes[1] = young_shared
            # lane 1 resumes over a 2-block shared radix prefix: its
            # re-admission replays from 2*BS, so preempting it loses
            # the LEAST exclusive work despite the younger t_admit
            srv._lane_shared[1] = held[:2]
            srv._lane_step[0] = 0
            srv._lane_step[1] = 2 * BS
            failures = []
            with srv._cv:
                n, m, run = srv._plan_burst_locked([], False, failures)
            assert run and n >= 0
            # rung 2 fires on the shared-prefix lane first ...
            assert freed[0] == 1
            assert srv._preemptions == 1
            assert srv._lanes[1] is None
            assert list(srv._queue) == [young_shared]
            assert young_shared.t_admit is None   # requeued cold
            # ... and the lone survivor, still unable to grow, gets
            # the NAMED retryable failure instead of a preempt loop
            assert freed == [1, 0]
            assert [r for r, _ in failures] == [old_plain]
            assert isinstance(failures[0][1], BlockPoolExhausted)
        finally:
            srv.close(1.0)

    def test_admit_age_breaks_equal_depth_ties(self, built):
        srv = self._idle(built)
        try:
            self._drain_pool(srv)
            freed = []
            srv._free_lane_locked = lambda slot: freed.append(slot)
            older = self._req(t_admit=1.0)
            younger = self._req(t_admit=2.0)
            srv._lanes[0] = younger
            srv._lanes[1] = older
            failures = []
            with srv._cv:
                srv._plan_burst_locked([], False, failures)
            # equal (zero) shared depth: the r13 discipline — the
            # YOUNGEST admission loses the least work
            assert freed[0] == 0
            assert list(srv._queue) == [younger]
        finally:
            srv.close(1.0)

    def test_bulk_leaf_evict_preferred_over_preemption(self, built):
        srv = self._idle(built)
        try:
            held = self._drain_pool(srv)
            spare = [held.pop(), held.pop()]
            evict_calls = []

            def fake_evict(n):
                # per-alloc growth asks for 1 leaf (none evictable);
                # rung 1's BULK ask finds the two reclaimable leaves
                evict_calls.append(n)
                if n < 2 or not spare:
                    return 0
                srv._blocks.free([spare.pop(), spare.pop()])
                return 2

            srv._radix.evict = fake_evict
            freed = []
            srv._free_lane_locked = lambda slot: freed.append(slot)
            srv._lanes[0] = self._req(1.0)
            srv._lanes[1] = self._req(2.0)
            srv._lane_blocks[0] = [held.pop()]
            srv._lane_blocks[1] = [held.pop()]
            srv._lane_step[0] = BS     # both at a block boundary
            srv._lane_step[1] = BS
            failures = []
            with srv._cv:
                n, m, run = srv._plan_burst_locked([], False, failures)
            # cache before work: both lanes grow into the evicted
            # blocks, nobody is preempted, the burst proceeds
            assert evict_calls == [1, 1, 2]
            assert freed == [] and not failures
            assert srv._preemptions == 0
            assert run and n == 4
            assert srv._lanes[0] is not None
            assert srv._lanes[1] is not None
        finally:
            srv.close(1.0)


class TestAnalysisContracts:
    def test_chunk_cursor_source_registered(self):
        srcs = absint.pool_index_sources()
        assert "chunk_cursor" in srcs
        assert srcs["chunk_cursor"].typestate == absint.TS_EXCLUSIVE
        assert srcs["chunk_cursor"].assumption == \
            "PromptPrefixCache.fresh-exclusive"

    @pytest.mark.parametrize("pick", [0, 1, 2, NPH - 1],
                             ids=["embed", "kv", "attn", "cross"])
    def test_chunk_phase_programs_discharge_provers(self, built,
                                                    pick):
        """The phase programs' staging/cross pool writes must chain
        to marked sources (chunk_cursor/host_indices) and keep the
        telemetry contract — zero error diagnostics from the
        ownership prover (PTA190/191/192) and PTA180."""
        prog = built["bundle"].serves[("chunked", pick)]
        bad = [d for d in run_checks(prog)
               if d.code in ("PTA180", "PTA190", "PTA191", "PTA192")
               and d.severity == ERROR]
        assert not bad, [(d.code, d.message) for d in bad]
