"""Property tests for the host allocator automaton
(models/decode_engine.HostBlockPool / PromptPrefixCache).

These classes are the HOST half of the pool-ownership bargain: the
ownership prover (PTA190/191/192, analysis/absint.py) proves device
programs lane-exclusive GIVEN the named invariants below, so the
invariants themselves must be machine-checked, not folklore:

* ``HostBlockPool.alloc-disjoint`` — a block is owned by exactly one
  lane between alloc and free: randomized alloc/free traces never
  yield overlapping live blocks, and bad lifetime transitions
  (double free, free of unallocated, out-of-range) raise the NAMED
  ``BlockLifetimeError`` instead of corrupting the free list;
* ``PromptPrefixCache.fresh-exclusive`` — a fresh entry starts at
  refcount 1 (the exclusive write window admission prefill uses);
  refcounts stay >= 0 (release below zero raises), shared entries
  (refcount > 1) are never ``writable``, and LRU eviction only ever
  touches UNPINNED entries (refcount == 0).

Plain ``random`` with fixed seeds — deterministic, no external
property-testing dependency."""
import random

import pytest

from paddle_tpu.models.decode_engine import (BlockLifetimeError,
                                             HostBlockPool,
                                             PromptPrefixCache)


class TestHostBlockPoolModel:
    def test_random_traces_keep_live_blocks_disjoint(self):
        for seed in range(8):
            rng = random.Random(1000 + seed)
            pool = HostBlockPool(rng.randint(1, 24))
            owned = {}          # lane -> set of blocks
            for _ in range(400):
                lane = rng.randrange(6)
                mine = owned.setdefault(lane, set())
                if rng.random() < 0.55:
                    b = pool.alloc()
                    if b is None:
                        assert pool.free_count == 0
                        continue
                    # alloc-disjoint: the block is live for NOBODY
                    for other, blocks in owned.items():
                        assert b not in blocks, (seed, lane, other)
                    mine.add(b)
                elif mine:
                    take = rng.sample(sorted(mine),
                                      rng.randint(1, len(mine)))
                    pool.free(take)
                    mine.difference_update(take)
                # global invariants after every step
                live = set().union(*owned.values()) if owned else set()
                assert pool.live_blocks() == live
                assert pool.in_use == len(live)
                assert pool.free_count + pool.in_use == pool.n_blocks

    def test_double_free_raises_named_error(self):
        pool = HostBlockPool(4)
        b = pool.alloc()
        pool.free([b])
        with pytest.raises(BlockLifetimeError, match="typestate"):
            pool.free([b])

    def test_free_of_unallocated_raises_named_error(self):
        # the satellite regression: this used to corrupt the free
        # list (the next alloc would hand one block to two lanes)
        pool = HostBlockPool(4)
        with pytest.raises(BlockLifetimeError):
            pool.free([2])
        with pytest.raises(BlockLifetimeError, match="outside"):
            pool.free([99])
        # a refused free leaves the pool consistent
        assert pool.free_count == 4 and pool.in_use == 0

    def test_failed_free_is_atomic(self):
        pool = HostBlockPool(4)
        a, b = pool.alloc(), pool.alloc()
        with pytest.raises(BlockLifetimeError):
            pool.free([a, a])   # second entry is a double free
        # NOTHING was freed: validation precedes mutation
        assert pool.typestate(a) == "exclusive"
        assert pool.typestate(b) == "exclusive"
        assert pool.free_count == 2
        pool.free([a, b])
        assert pool.free_count == 4

    def test_typestate_surface(self):
        pool = HostBlockPool(2)
        b = pool.alloc()
        assert pool.typestate(b) == "exclusive"
        pool.free([b])
        assert pool.typestate(b) == "free"


class TestPromptPrefixCacheModel:
    def _prompt(self, rng):
        return tuple(rng.randrange(50) for _ in range(4))

    def test_random_traces_keep_refcounts_and_eviction_legal(self):
        for seed in range(8):
            rng = random.Random(2000 + seed)
            pc = PromptPrefixCache(rng.randint(1, 6), chunk_tokens=2)
            refs = {}           # entry -> model refcount
            prompts = [self._prompt(rng) for _ in range(8)]
            for _ in range(300):
                p = rng.choice(prompts)
                r = rng.random()
                tier, entry = pc.lookup(p)
                if r < 0.5:
                    if tier == "hit":
                        e = pc.acquire_hit(p)
                        refs[e] = refs.get(e, 0) + 1
                    else:
                        before = dict(refs)
                        e = pc.acquire_fresh(p, partial=(
                            tier == "partial"))
                        if e is None:
                            # every entry pinned: nothing evictable
                            assert all(v > 0 for v in before.values())
                            assert len(before) >= pc.n_entries
                            continue
                        # fresh-exclusive: the entry was NOT live
                        # (eviction only touches unpinned entries)
                        assert before.get(e, 0) == 0, (seed, e)
                        refs[e] = 1
                        assert pc.refcount(e) == 1
                        assert pc.writable(e)
                        assert pc.typestate(e) == "exclusive"
                else:
                    live = [e for e, v in refs.items() if v > 0]
                    if live:
                        e = rng.choice(live)
                        pc.release(e)
                        refs[e] -= 1
                # invariants after every step
                for e, v in refs.items():
                    assert pc.refcount(e) == v and v >= 0
                    assert pc.is_shared(e) == (v > 1)
                    assert pc.writable(e) == (v <= 1)
                assert pc.in_use == sum(1 for v in refs.values()
                                        if v > 0)
                assert pc.in_use <= pc.n_entries

    def test_release_below_zero_raises_named_error(self):
        pc = PromptPrefixCache(2, chunk_tokens=2)
        e = pc.acquire_fresh((1, 2, 3))
        pc.release(e)
        with pytest.raises(BlockLifetimeError, match="refcount"):
            pc.release(e)

    def test_shared_entry_is_not_writable(self):
        # the host half of PTA192's read-only-while-shared: two lanes
        # share one prompt entry -> refcount 2 -> not writable; after
        # one release it returns to the exclusive (COW-legal) state
        pc = PromptPrefixCache(2, chunk_tokens=2)
        p = (5, 5, 5)
        e = pc.acquire_fresh(p)
        assert pc.typestate(e) == "exclusive" and pc.writable(e)
        assert pc.acquire_hit(p) == e
        assert pc.typestate(e) == "shared"
        assert pc.is_shared(e) and not pc.writable(e)
        pc.release(e)
        assert pc.typestate(e) == "exclusive" and pc.writable(e)

    def test_eviction_only_touches_unpinned(self):
        pc = PromptPrefixCache(2, chunk_tokens=2)
        p1, p2, p3 = (1, 1), (2, 2), (3, 3)
        e1 = pc.acquire_fresh(p1)
        e2 = pc.acquire_fresh(p2)
        # both pinned: a miss has nothing to evict
        assert pc.acquire_fresh(p3) is None
        pc.release(e1)
        # p1 now unpinned: it is the only legal victim
        e3 = pc.acquire_fresh(p3)
        assert e3 == e1 and pc.evictions == 1
        assert pc.lookup(p1) == ("miss", None)
        assert pc.lookup(p2)[0] == "hit"
        assert pc.refcount(e2) == 1
