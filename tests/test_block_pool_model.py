"""Property tests for the host allocator automaton
(models/decode_engine.HostBlockPool / PromptPrefixCache).

These classes are the HOST half of the pool-ownership bargain: the
ownership prover (PTA190/191/192, analysis/absint.py) proves device
programs lane-exclusive GIVEN the named invariants below, so the
invariants themselves must be machine-checked, not folklore:

* ``HostBlockPool.alloc-disjoint`` — a block is owned by exactly one
  lane between alloc and free: randomized alloc/free traces never
  yield overlapping live blocks, and bad lifetime transitions
  (double free, free of unallocated, out-of-range) raise the NAMED
  ``BlockLifetimeError`` instead of corrupting the free list;
* ``PromptPrefixCache.fresh-exclusive`` — a fresh entry starts at
  refcount 1 (the exclusive write window admission prefill uses);
  refcounts stay >= 0 (release below zero raises), shared entries
  (refcount > 1) are never ``writable``, and LRU eviction only ever
  touches UNPINNED entries (refcount == 0).

The FAST lane is exhaustive: analysis/protomodel.py explores every
reachable interleaving of each allocator machine at small bounds
(``TestExhaustiveProtocolChecks`` — proof-up-to-bound, with seeded-bug
mutation tests showing the harness actually catches dropped decrefs).
The big randomized sweeps that used to carry this weight remain as the
SLOW-lane belt-and-braces (larger pools, longer traces than the
explorer can enumerate). Plain ``random`` with fixed seeds —
deterministic, no external property-testing dependency."""
import random

import pytest

from paddle_tpu.analysis import protomodel

from paddle_tpu.models.decode_engine import (BlockLifetimeError,
                                             HostBlockPool,
                                             PromptPrefixCache,
                                             RadixBlockTree)


class TestExhaustiveProtocolChecks:
    """Every reachable interleaving at small bounds (the protomodel
    explorer) — the fast-lane replacement for sampling: refcount
    conservation in every state, drain-to-free from every state, no
    deadlock, no lifetime raise. The mutation tests seed a real bug
    class into one action and assert the harness CATCHES it with a
    minimal trace — a green exhaustive run means something only if a
    red one is demonstrably reachable."""

    def test_block_pool_every_interleaving_conserves_refcounts(self):
        r = protomodel.explore(protomodel.block_pool_protocol(
            n_blocks=2, n_lanes=2, pages=1))
        assert r.ok and not r.truncated, (
            r.counterexample and r.counterexample.format())

    def test_prefix_cache_every_interleaving_conserves_entries(self):
        r = protomodel.explore(protomodel.prefix_cache_protocol(
            n_entries=2, n_prompts=2, n_clients=2))
        assert r.ok and not r.truncated, (
            r.counterexample and r.counterexample.format())

    def test_radix_every_interleaving_conserves_holds(self):
        r = protomodel.explore(protomodel.radix_protocol(
            n_blocks=3, n_lanes=2))
        assert r.ok and not r.truncated, (
            r.counterexample and r.counterexample.format())

    def test_mutation_dropped_decref_is_caught(self):
        # seed the leak class PTA201 exists for: a retire path that
        # forgets to decref the lane's blocks. The explorer must
        # refute it with a minimal trace, via the refcount invariant
        # (the state lies about holds) and/or the drain leak check.
        proto = protomodel.block_pool_protocol(
            n_blocks=2, n_lanes=2, pages=1)

        def leaky_retire(s, li=0):
            # drops the hold WITHOUT releasing the refcount
            s["lanes"][li].update(blocks=[], shared=[])

        proto.actions = [
            a if not a.name.startswith("retire[0")
            else protomodel.Action(a.name, a.guard, leaky_retire)
            for a in proto.actions]
        r = protomodel.explore(proto)
        assert not r.ok and r.counterexample is not None
        assert r.counterexample.kind in ("invariant", "leak")
        assert "refcount" in r.counterexample.detail
        # minimal: alloc then the leaky retire, nothing longer
        assert len(r.counterexample.trace) == 2

    def test_mutation_double_release_is_caught_by_typestate(self):
        # the opposite bug: a release path that decrefs twice. The
        # REAL allocator's typestate machine must raise the named
        # BlockLifetimeError, surfacing as a `lifetime` violation.
        proto = protomodel.block_pool_protocol(
            n_blocks=2, n_lanes=1, pages=1)

        def double_retire(s):
            lane = s["lanes"][0]
            for b in lane["blocks"]:
                s["pool"].decref(b)
                s["pool"].decref(b)
            lane.update(blocks=[], shared=[])

        proto.actions = [
            a if not a.name.startswith("retire[0")
            else protomodel.Action(a.name, a.guard, double_retire)
            for a in proto.actions]
        r = protomodel.explore(proto)
        assert not r.ok and r.counterexample.kind == "lifetime"


class TestHostBlockPoolModel:
    @pytest.mark.slow
    def test_random_traces_keep_live_blocks_disjoint(self):
        for seed in range(8):
            rng = random.Random(1000 + seed)
            pool = HostBlockPool(rng.randint(1, 24))
            owned = {}          # lane -> set of blocks
            for _ in range(400):
                lane = rng.randrange(6)
                mine = owned.setdefault(lane, set())
                if rng.random() < 0.55:
                    b = pool.alloc()
                    if b is None:
                        assert pool.free_count == 0
                        continue
                    # alloc-disjoint: the block is live for NOBODY
                    for other, blocks in owned.items():
                        assert b not in blocks, (seed, lane, other)
                    mine.add(b)
                elif mine:
                    take = rng.sample(sorted(mine),
                                      rng.randint(1, len(mine)))
                    pool.free(take)
                    mine.difference_update(take)
                # global invariants after every step
                live = set().union(*owned.values()) if owned else set()
                assert pool.live_blocks() == live
                assert pool.in_use == len(live)
                assert pool.free_count + pool.in_use == pool.n_blocks

    def test_double_free_raises_named_error(self):
        pool = HostBlockPool(4)
        b = pool.alloc()
        pool.free([b])
        with pytest.raises(BlockLifetimeError, match="typestate"):
            pool.free([b])

    def test_free_of_unallocated_raises_named_error(self):
        # the satellite regression: this used to corrupt the free
        # list (the next alloc would hand one block to two lanes)
        pool = HostBlockPool(4)
        with pytest.raises(BlockLifetimeError):
            pool.free([2])
        with pytest.raises(BlockLifetimeError, match="outside"):
            pool.free([99])
        # a refused free leaves the pool consistent
        assert pool.free_count == 4 and pool.in_use == 0

    def test_failed_free_is_atomic(self):
        pool = HostBlockPool(4)
        a, b = pool.alloc(), pool.alloc()
        with pytest.raises(BlockLifetimeError):
            pool.free([a, a])   # second entry is a double free
        # NOTHING was freed: validation precedes mutation
        assert pool.typestate(a) == "exclusive"
        assert pool.typestate(b) == "exclusive"
        assert pool.free_count == 2
        pool.free([a, b])
        assert pool.free_count == 4

    def test_typestate_surface(self):
        pool = HostBlockPool(2)
        b = pool.alloc()
        assert pool.typestate(b) == "exclusive"
        pool.free([b])
        assert pool.typestate(b) == "free"


class TestPromptPrefixCacheModel:
    def _prompt(self, rng):
        return tuple(rng.randrange(50) for _ in range(4))

    @pytest.mark.slow
    def test_random_traces_keep_refcounts_and_eviction_legal(self):
        for seed in range(8):
            rng = random.Random(2000 + seed)
            pc = PromptPrefixCache(rng.randint(1, 6), chunk_tokens=2)
            refs = {}           # entry -> model refcount
            prompts = [self._prompt(rng) for _ in range(8)]
            for _ in range(300):
                p = rng.choice(prompts)
                r = rng.random()
                tier, entry = pc.lookup(p)
                if r < 0.5:
                    if tier == "hit":
                        e = pc.acquire_hit(p)
                        refs[e] = refs.get(e, 0) + 1
                    else:
                        before = dict(refs)
                        e = pc.acquire_fresh(p, partial=(
                            tier == "partial"))
                        if e is None:
                            # every entry pinned: nothing evictable
                            assert all(v > 0 for v in before.values())
                            assert len(before) >= pc.n_entries
                            continue
                        # fresh-exclusive: the entry was NOT live
                        # (eviction only touches unpinned entries)
                        assert before.get(e, 0) == 0, (seed, e)
                        refs[e] = 1
                        assert pc.refcount(e) == 1
                        assert pc.writable(e)
                        assert pc.typestate(e) == "exclusive"
                else:
                    live = [e for e, v in refs.items() if v > 0]
                    if live:
                        e = rng.choice(live)
                        pc.release(e)
                        refs[e] -= 1
                # invariants after every step
                for e, v in refs.items():
                    assert pc.refcount(e) == v and v >= 0
                    assert pc.is_shared(e) == (v > 1)
                    assert pc.writable(e) == (v <= 1)
                assert pc.in_use == sum(1 for v in refs.values()
                                        if v > 0)
                assert pc.in_use <= pc.n_entries

    def test_release_below_zero_raises_named_error(self):
        pc = PromptPrefixCache(2, chunk_tokens=2)
        e = pc.acquire_fresh((1, 2, 3))
        pc.release(e)
        with pytest.raises(BlockLifetimeError, match="refcount"):
            pc.release(e)

    def test_shared_entry_is_not_writable(self):
        # the host half of PTA192's read-only-while-shared: two lanes
        # share one prompt entry -> refcount 2 -> not writable; after
        # one release it returns to the exclusive (COW-legal) state
        pc = PromptPrefixCache(2, chunk_tokens=2)
        p = (5, 5, 5)
        e = pc.acquire_fresh(p)
        assert pc.typestate(e) == "exclusive" and pc.writable(e)
        assert pc.acquire_hit(p) == e
        assert pc.typestate(e) == "shared"
        assert pc.is_shared(e) and not pc.writable(e)
        pc.release(e)
        assert pc.typestate(e) == "exclusive" and pc.writable(e)

    def test_eviction_only_touches_unpinned(self):
        pc = PromptPrefixCache(2, chunk_tokens=2)
        p1, p2, p3 = (1, 1), (2, 2), (3, 3)
        e1 = pc.acquire_fresh(p1)
        e2 = pc.acquire_fresh(p2)
        # both pinned: a miss has nothing to evict
        assert pc.acquire_fresh(p3) is None
        pc.release(e1)
        # p1 now unpinned: it is the only legal victim
        e3 = pc.acquire_fresh(p3)
        assert e3 == e1 and pc.evictions == 1
        assert pc.lookup(p1) == ("miss", None)
        assert pc.lookup(p2)[0] == "hit"
        assert pc.refcount(e2) == 1


class TestRadixBlockTreeModel:
    """Randomized trace testing of the refcounted radix tree over
    HostBlockPool (the ISSUE 16 protocol): lanes acquire shared
    chains read-only + alloc exclusive tails, finished chains are
    inserted (the tree adopts with its OWN ref; existing node wins),
    eviction unpins tree-only leaves. The model tracks every holder
    of every block and cross-checks the pool's refcounts/typestates
    after every operation."""

    BS = 2

    def _histories(self, rng):
        """Per-prompt deterministic decode streams that SHARE a
        prefix and then branch (greedy decode determinism is what
        makes radix chains shareable at all): prompt -> two variants
         'a'/'b' diverging after a random number of chunks."""
        out = {}
        for p in range(3):
            prompt = (100 + p, 200 + p)
            common = [rng.randrange(3, 50)
                      for _ in range(self.BS * rng.randint(1, 4))]
            out[prompt] = {
                v: common + [rng.randrange(3, 50) + 50 * i
                             for i in range(self.BS * 5)]
                for i, v in enumerate(("a", "b"))}
        return out

    def _check(self, pool, tree, lanes):
        """Global cross-check: pool refcounts == model holder counts,
        writability == single ownership, lane TAILS disjoint."""
        holders = {b: 1 for b in tree.tree_blocks()}
        tails = []
        for ln in lanes.values():
            for b in ln["shared"] + ln["tail"]:
                holders[b] = holders.get(b, 0) + 1
            tails.append(set(ln["tail"]))
        for b in range(pool.n_blocks):
            want = holders.get(b, 0)
            assert pool.refcount(b) == want, (b, want,
                                              pool.refcount(b))
            assert (pool.typestate(b) != "free") == (want > 0)
            if want > 0:
                # refcount 1 <=> writable <=> exactly one holder
                assert pool.writable(b) == (want == 1)
        # live blocks never overlap across chains in the WRITABLE
        # position: exclusive tails are pairwise disjoint
        for i in range(len(tails)):
            for j in range(i + 1, len(tails)):
                assert not (tails[i] & tails[j]), (tails[i],
                                                   tails[j])
        assert pool.free_count + pool.in_use == pool.n_blocks

    @pytest.mark.slow
    def test_random_traces_hold_radix_invariants(self):
        for seed in range(6):
            rng = random.Random(3000 + seed)
            pool = HostBlockPool(rng.randint(10, 28))
            tree = RadixBlockTree(pool, self.BS)
            hist = self._histories(rng)
            lanes, next_lane = {}, 0
            for _ in range(250):
                r = rng.random()
                if r < 0.45:  # admit: acquire shared + alloc tail
                    prompt = rng.choice(list(hist))
                    var = rng.choice(("a", "b"))
                    n = rng.randrange(0, 10)
                    toks = hist[prompt][var][:n]
                    shared = tree.acquire(prompt, toks)
                    want_tail = rng.randint(1, 2)
                    tail = []
                    while len(tail) < want_tail:
                        b = pool.alloc()
                        if b is None:
                            break
                        tail.append(b)
                    if len(tail) < want_tail:
                        # exhausted: back out ATOMICALLY (the
                        # server's blocked-admission path)
                        for b in reversed(tail):
                            pool.decref(b)
                        tree.release(shared)
                    else:
                        lanes[next_lane] = {
                            "prompt": prompt, "var": var,
                            "shared": shared, "tail": tail}
                        next_lane += 1
                elif r < 0.75 and lanes:  # finish: insert + free
                    lid = rng.choice(list(lanes))
                    ln = lanes.pop(lid)
                    chain = ln["shared"] + ln["tail"]
                    # the lane decoded along its deterministic
                    # stream: every block in the chain is FULL
                    toks = hist[ln["prompt"]][ln["var"]][
                        :len(chain) * self.BS]
                    before_tree = tree.tree_blocks()
                    adopted = tree.insert(ln["prompt"], toks, chain)
                    # existing node wins: newly adopted blocks are
                    # exactly the chain blocks not already in a node
                    gained = tree.tree_blocks() - before_tree
                    assert len(gained) == adopted
                    assert gained <= set(chain)
                    tree.release(ln["shared"])
                    for b in reversed(ln["tail"]):
                        pool.decref(b)
                elif r < 0.9:  # evict
                    lane_held = {b for ln in lanes.values()
                                 for b in ln["shared"] + ln["tail"]}
                    before = pool.free_count
                    freed = tree.evict(rng.randint(1, 3))
                    assert pool.free_count == before + freed
                    # eviction never touches a pinned block
                    for b in lane_held:
                        assert pool.typestate(b) != "free", b
                else:  # release a lane WITHOUT inserting (failure/
                    # preemption path: nothing joins the tree)
                    if lanes:
                        lid = rng.choice(list(lanes))
                        ln = lanes.pop(lid)
                        tree.release(ln["shared"])
                        for b in reversed(ln["tail"]):
                            pool.decref(b)
                self._check(pool, tree, lanes)
            # drain: release every lane, then evict the whole tree —
            # the pool must come back to fully free (no leaks)
            for ln in lanes.values():
                tree.release(ln["shared"])
                for b in reversed(ln["tail"]):
                    pool.decref(b)
            tree.evict(pool.n_blocks)
            assert pool.free_count == pool.n_blocks
            assert tree.tree_blocks() == set()

    def test_refcounts_never_negative(self):
        pool = HostBlockPool(2)
        b = pool.alloc()
        pool.decref(b)
        with pytest.raises(BlockLifetimeError, match="negative"):
            pool.decref(b)
        with pytest.raises(BlockLifetimeError, match="refcount 0"):
            pool.incref(b)

    def test_shared_block_is_not_writable_cow_restores(self):
        # host half of PTA192: a first write into a shared block must
        # COW — the shared source is never writable; the fresh copy
        # is; decref'ing the source back to one owner restores its
        # writability
        pool = HostBlockPool(4)
        src = pool.alloc()
        pool.incref(src)                 # tree/another lane adopts
        assert not pool.writable(src)
        dst = pool.alloc()               # the COW destination
        assert pool.writable(dst)
        pool.decref(src)                 # the writing lane lets go
        assert pool.writable(src)        # sole owner again

    def test_strict_free_rejects_shared_blocks(self):
        # the legacy lane-release path must NOT yank a radix-adopted
        # block: free() is exclusive-only, decref is the radix-aware
        # release
        pool = HostBlockPool(2)
        b = pool.alloc()
        pool.incref(b)
        with pytest.raises(BlockLifetimeError, match="shared"):
            pool.free([b])
        assert pool.refcount(b) == 2     # the refused free mutated
        pool.decref(b)                   # nothing
        pool.free([b])

    def test_insert_underflow_is_atomic(self):
        pool = HostBlockPool(4)
        tree = RadixBlockTree(pool, 2)
        blocks = [pool.alloc(), pool.alloc()]
        with pytest.raises(BlockLifetimeError, match="radix insert"):
            tree.insert((1, 2), [5, 6, 7, 8, 9, 10], blocks)
        # NOTHING was adopted: validation precedes mutation
        assert tree.tree_blocks() == set()
        assert all(pool.refcount(b) == 1 for b in blocks)

    def test_existing_node_wins_duplicate_stays_lane_owned(self):
        # two lanes decode the SAME continuation (greedy twins): the
        # first insert adopts, the second adopts nothing and the
        # duplicate blocks remain the lane's to free normally
        pool = HostBlockPool(8)
        tree = RadixBlockTree(pool, 2)
        toks = [7, 8, 9, 10]
        a = [pool.alloc(), pool.alloc()]
        assert tree.insert((1,), toks, a) == 2
        b = [pool.alloc(), pool.alloc()]
        assert tree.insert((1,), toks, b) == 0
        assert tree.tree_blocks() == set(a)
        for blk in reversed(b):
            pool.decref(blk)             # duplicates: plain free
        for blk in reversed(a):
            pool.decref(blk)             # lane refs; tree's survive
        assert pool.in_use == 2          # the adopted chain lives on
        # a later acquire maps the surviving chain
        got = tree.acquire((1,), toks)
        assert got == a
        tree.release(got)

    def test_evict_deepest_leaf_first_never_interior(self):
        pool = HostBlockPool(8)
        tree = RadixBlockTree(pool, 2)
        toks = [1, 2, 3, 4, 5, 6]
        chain = [pool.alloc() for _ in range(3)]
        tree.insert((9,), toks, chain)
        for b in reversed(chain):
            pool.decref(b)               # lane gone; tree-only now
        # a lane pins the 2-block prefix: only the depth-3 leaf is
        # evictable, interior nodes under the pin never are
        held = tree.acquire((9,), toks[:4])
        assert held == chain[:2]
        assert tree.evict(99) == 1
        assert pool.typestate(chain[2]) == "free"
        assert tree.tree_blocks() == set(chain[:2])
        tree.release(held)
        assert tree.evict(99) == 2       # unpinned: deepest first
        assert pool.free_count == pool.n_blocks
