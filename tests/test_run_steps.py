"""Executor.run_steps: K training steps as ONE device-resident
lax.scan (the TPU-native reading of the reference's C++
while-over-steps hot loop, reference framework/executor.cc
RunPreparedContext, + layers/io.py double_buffer H2D staging).

Acceptance bars (ISSUE r6): run_steps(K) loss trajectories match K
sequential Executor.run calls to <=1e-6 on the mnist-fc and
transformer-base families -- including a dropout program (the
step-keyed noise must advance identically inside the scan) and an AMP
program -- and non-scannable programs fall back to the per-step path
with a NAMED reason instead of mis-executing.
"""
import numpy as np
import pytest

import paddle_tpu as fluid


def _fresh():
    fluid._reset_global_scope()
    from paddle_tpu import unique_name
    unique_name.switch()
    fluid.seed(11)


def _losses_sequential(prog, startup, loss, feeds, scope=None):
    """K sequential run() calls -- the oracle trajectory."""
    exe = fluid.Executor(fluid.CPUPlace())
    sc = scope or fluid.Scope()
    exe.run(startup, scope=sc)
    out = []
    for f in feeds:
        l, = exe.run(prog, feed=f, fetch_list=[loss], scope=sc)
        out.append(float(np.asarray(l).reshape(-1)[0]))
    return out, sc


def _losses_scanned(prog, startup, loss, feeds, same_feed=None,
                    steps=None):
    exe = fluid.Executor(fluid.CPUPlace())
    sc = fluid.Scope()
    exe.run(startup, scope=sc)
    if same_feed is not None:
        out = exe.run_steps(prog, feed=same_feed, fetch_list=[loss],
                            steps=steps, scope=sc)
    else:
        out = exe.run_steps(prog, feed=feeds, fetch_list=[loss],
                            scope=sc)
    assert exe.last_run_steps_fallback is None, \
        exe.last_run_steps_fallback
    return list(np.asarray(out[0]).reshape(-1).astype(np.float64)), sc


def _mnist_fc():
    from paddle_tpu.models import mnist as M

    main, startup, loss, _acc = M.build_program(use_conv=False)
    with fluid.program_guard(main, startup):
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    return main, startup, loss


def _mnist_feeds(k, batch=16):
    r = np.random.RandomState(0)
    feeds = []
    for _ in range(k):
        lab = r.randint(0, 10, (batch, 1)).astype(np.int64)
        img = r.randn(batch, 784).astype(np.float32) * 0.1
        img[np.arange(batch), lab[:, 0]] += 2.0
        feeds.append({"img": img, "label": lab})
    return feeds


def _tiny_transformer(dropout_rate=0.0):
    from paddle_tpu.models import transformer as T

    main, startup, cost = T.build_program(
        seq_len=8, d_model=16, n_heads=2, n_layers=1, d_inner=32,
        vocab=64, dropout_rate=dropout_rate, with_optimizer=True,
        learning_rate=0.5, warmup_steps=100)
    return main, startup, cost


def _transformer_feed(batch=4, seq=8, vocab=64, seed=0):
    r = np.random.RandomState(seed)
    return {
        "src_ids": r.randint(0, vocab, (batch, seq)).astype(np.int64),
        "tgt_ids": r.randint(0, vocab, (batch, seq)).astype(np.int64),
        "label": r.randint(0, vocab, (batch, seq)).astype(np.int64),
    }


class TestRunStepsParity:
    def test_mnist_fc_same_feed(self):
        """Constant-feed mode: one dict, steps=K."""
        _fresh()
        prog, startup, loss = _mnist_fc()
        feed = _mnist_feeds(1)[0]
        K = 5
        seq, _ = _losses_sequential(prog, startup, loss, [feed] * K)
        scan, _ = _losses_scanned(prog, startup, loss, None,
                                  same_feed=feed, steps=K)
        np.testing.assert_allclose(scan, seq, rtol=0, atol=1e-6)
        assert seq[-1] < seq[0]  # the trajectory actually trains

    def test_mnist_fc_per_step_feeds_and_final_state(self):
        """Staged mode: K distinct batches enter as scan xs; the
        post-window persistable state matches the sequential path."""
        _fresh()
        prog, startup, loss = _mnist_fc()
        feeds = _mnist_feeds(4)
        seq, sc_seq = _losses_sequential(prog, startup, loss, feeds)
        scan, sc_scan = _losses_scanned(prog, startup, loss, feeds)
        np.testing.assert_allclose(scan, seq, rtol=0, atol=1e-6)
        for name in ("fc_0.w_0",):
            a, b = sc_seq._get(name), sc_scan._get(name)
            if a is None or b is None:
                continue
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=0, atol=1e-6)

    def test_dropout_step_key_parity(self):
        """Sampling ops inside the scan must draw the EXACT per-step
        noise of sequential runs: the step key advances once per scan
        iteration via the same split the per-step executor does."""
        _fresh()
        prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(prog, startup):
            x = fluid.layers.data("x", shape=[8], dtype="float32")
            y = fluid.layers.data("y", shape=[1], dtype="int64")
            h = fluid.layers.fc(x, 32, act="relu")
            h = fluid.layers.dropout(h, dropout_prob=0.4)
            logits = fluid.layers.fc(h, 4)
            loss = fluid.layers.mean(
                fluid.layers.softmax_with_cross_entropy(logits, y))
            fluid.optimizer.Adam(0.02).minimize(loss)
        r = np.random.RandomState(1)
        feed = {"x": r.randn(16, 8).astype(np.float32),
                "y": r.randint(0, 4, (16, 1)).astype(np.int64)}
        K = 6
        seq, _ = _losses_sequential(prog, startup, loss, [feed] * K)
        scan, _ = _losses_scanned(prog, startup, loss, None,
                                  same_feed=feed, steps=K)
        # dropout noise diverging would show up WAY above 1e-6
        np.testing.assert_allclose(scan, seq, rtol=0, atol=1e-6)

    def test_transformer_with_dropout(self):
        _fresh()
        prog, startup, cost = _tiny_transformer(dropout_rate=0.1)
        feed = _transformer_feed()
        K = 3
        seq, _ = _losses_sequential(prog, startup, cost, [feed] * K)
        scan, _ = _losses_scanned(prog, startup, cost, None,
                                  same_feed=feed, steps=K)
        np.testing.assert_allclose(scan, seq, rtol=0, atol=1e-6)

    def test_transformer_amp(self):
        """bf16 AMP casts happen at trace time (run_op), so the scan
        body sees the identical cast placement as the per-step path."""
        from paddle_tpu import amp

        _fresh()
        prog, startup, cost = _tiny_transformer()
        feed = _transformer_feed(seed=2)
        K = 3
        with amp.amp_guard(True):
            seq, _ = _losses_sequential(prog, startup, cost,
                                        [feed] * K)
            scan, _ = _losses_scanned(prog, startup, cost, None,
                                      same_feed=feed, steps=K)
        np.testing.assert_allclose(scan, seq, rtol=0, atol=1e-6)


class TestRunStepsFallback:
    def test_py_reader_program_falls_back_with_named_reason(self):
        """io_callback reader ops pop one batch per step from host
        state -- unlowerable into lax.scan; the named reason fires and
        the per-step path still trains correctly."""
        _fresh()
        B = 8
        prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(prog, startup):
            reader = fluid.layers.py_reader(
                capacity=4, shapes=[(B, 8), (B, 1)],
                dtypes=["float32", "int64"])
            x, y = fluid.layers.read_file(reader)
            logits = fluid.layers.fc(x, 4)
            loss = fluid.layers.mean(
                fluid.layers.softmax_with_cross_entropy(logits, y))
            fluid.optimizer.SGD(0.1).minimize(loss)
        r = np.random.RandomState(0)
        batches = [(r.randn(B, 8).astype(np.float32),
                    r.randint(0, 4, (B, 1)).astype(np.int64))
                   for _ in range(8)]
        reader.decorate_tensor_provider(lambda: iter(batches))
        exe = fluid.Executor(fluid.CPUPlace())
        sc = fluid.Scope()
        exe.run(startup, scope=sc)
        K = 3
        out = exe.run_steps(prog, fetch_list=[loss], steps=K, scope=sc)
        reason = exe.last_run_steps_fallback
        assert reason is not None
        assert "host" in reason and "lax.scan" in reason
        assert np.asarray(out[0]).shape[0] == K
        assert np.all(np.isfinite(np.asarray(out[0])))

    def test_go_program_falls_back(self):
        _fresh()
        seen = []

        def record(arr):
            seen.append(np.asarray(arr).copy())
            return np.asarray(arr)

        prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(prog, startup):
            x = fluid.layers.data("x", shape=[4], dtype="float32")
            y = fluid.layers.scale(x, scale=2.0)
            with fluid.layers.Go():
                sink = prog.current_block().create_var(
                    name="rs_go_sink", shape=[-1, 4], dtype="float32")
                fluid.layers.py_func(record, y, out=sink)
            loss = fluid.layers.mean(y)
        exe = fluid.Executor(fluid.CPUPlace())
        sc = fluid.Scope()
        exe.run(startup, scope=sc)
        feed = {"x": np.ones((2, 4), np.float32)}
        K = 3
        out = exe.run_steps(prog, feed=feed, fetch_list=[loss],
                            steps=K, scope=sc)
        assert exe.last_run_steps_fallback is not None
        assert "'go'" in exe.last_run_steps_fallback or \
            "go" in exe.last_run_steps_fallback
        np.testing.assert_allclose(np.asarray(out[0]).reshape(-1),
                                   [2.0] * K, rtol=1e-6)
        for t in getattr(exe, "_go_threads", []):
            t.join(10)
        assert len(seen) == K  # the go block fired once per step

    def test_host_op_inside_sub_block_is_caught(self):
        """The scannability walk must recurse into control-flow
        sub-blocks: a host op inside a While body forces fallback."""
        _fresh()
        prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(prog, startup):
            x = fluid.layers.data("x", shape=[4], dtype="float32")
            i = fluid.layers.fill_constant(shape=[1], dtype="int32",
                                           value=0)
            n = fluid.layers.fill_constant(shape=[1], dtype="int32",
                                           value=2)
            cond = fluid.layers.less_than(i, n)
            w = fluid.layers.While(cond)
            with w.block():
                fluid.layers.Print(x, message="inside-while")
                fluid.layers.increment(i, value=1, in_place=True)
                fluid.layers.less_than(i, n, cond=cond)
            loss = fluid.layers.mean(x)
        exe = fluid.Executor(fluid.CPUPlace())
        sc = fluid.Scope()
        exe.run(startup, scope=sc)
        from paddle_tpu.core.executor import _scan_fallback_reason
        reason = _scan_fallback_reason(prog)
        assert reason is not None and "print" in reason

    def test_compiled_program_falls_back(self):
        _fresh()
        prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(prog, startup):
            x = fluid.layers.data("x", shape=[8], dtype="float32")
            y = fluid.layers.data("y", shape=[1], dtype="int64")
            logits = fluid.layers.fc(x, 4)
            loss = fluid.layers.mean(
                fluid.layers.softmax_with_cross_entropy(logits, y))
            fluid.optimizer.SGD(0.1).minimize(loss)
        exe = fluid.Executor(fluid.TPUPlace())
        exe.run(startup)
        cp = fluid.CompiledProgram(prog).with_data_parallel(
            loss_name=loss.name)
        r = np.random.RandomState(0)
        feed = {"x": r.randn(16, 8).astype(np.float32),
                "y": r.randint(0, 4, (16, 1)).astype(np.int64)}
        out = exe.run_steps(cp, feed=feed, fetch_list=[loss.name],
                            steps=2)
        assert exe.last_run_steps_fallback is not None
        assert "CompiledProgram" in exe.last_run_steps_fallback
        assert np.asarray(out[0]).shape[0] == 2


class TestRunStepsContract:
    def test_steps_required_for_single_dict(self):
        _fresh()
        prog, startup, loss = _mnist_fc()
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        with pytest.raises(ValueError, match="steps"):
            exe.run_steps(prog, feed=_mnist_feeds(1)[0],
                          fetch_list=[loss])

    def test_mismatched_feed_keys_rejected(self):
        _fresh()
        prog, startup, loss = _mnist_fc()
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        f1, f2 = _mnist_feeds(2)
        del f2["label"]
        with pytest.raises(ValueError, match="same variable names"):
            exe.run_steps(prog, feed=[f1, f2], fetch_list=[loss])

    def test_stacked_fetch_shape(self):
        _fresh()
        prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(prog, startup):
            x = fluid.layers.data("x", shape=[4], dtype="float32")
            out = fluid.layers.scale(x, scale=3.0)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        feed = {"x": np.ones((2, 4), np.float32)}
        res = exe.run_steps(prog, feed=feed, fetch_list=[out], steps=4)
        assert exe.last_run_steps_fallback is None
        assert np.asarray(res[0]).shape == (4, 2, 4)
        np.testing.assert_allclose(np.asarray(res[0]),
                                   np.full((4, 2, 4), 3.0))

    def test_return_numpy_false_returns_device_arrays(self):
        import jax

        _fresh()
        prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(prog, startup):
            x = fluid.layers.data("x", shape=[4], dtype="float32")
            out = fluid.layers.scale(x, scale=2.0)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        res = exe.run_steps(prog, feed={"x": np.ones((2, 4),
                                                     np.float32)},
                            fetch_list=[out], steps=3,
                            return_numpy=False)
        assert isinstance(res[0], jax.Array)


class TestDoubleBufferedFeed:
    def test_pyreader_double_buffer_stages_on_device(self):
        """use_double_buffer=True: the fill thread device_puts each
        batch, so the consumer pops device-resident arrays (H2D of
        batch k+1 overlaps step k)."""
        import jax

        _fresh()
        prog = fluid.Program()
        with fluid.program_guard(prog, fluid.Program()):
            x = fluid.layers.data("px", shape=[4], dtype="float32")
        from paddle_tpu.reader import PyReader

        batches = [[(np.full(4, i, np.float32),)] for i in range(5)]
        rd = PyReader(feed_list=[x], capacity=4,
                      use_double_buffer=True)
        rd.decorate_sample_list_generator(lambda: iter(batches))
        got = list(rd)
        assert len(got) == 5
        for i, item in enumerate(got):
            assert isinstance(item["px"], jax.Array)
            np.testing.assert_allclose(np.asarray(item["px"]),
                                       np.full((1, 4), i))

    def test_pyreader_reset_stops_fill_thread_no_stale_batches(self):
        """reset() mid-epoch must signal + join the fill thread: the
        old behavior abandoned it still blocked on the bounded queue,
        and it kept interleaving epoch-A batches into epoch B."""
        import time

        _fresh()
        prog = fluid.Program()
        with fluid.program_guard(prog, fluid.Program()):
            x = fluid.layers.data("rx", shape=[2], dtype="float32")
        from paddle_tpu.reader import PyReader

        def slow_epoch(tag, n=50):
            def gen():
                for _ in range(n):
                    time.sleep(0.002)
                    yield [(np.full(2, tag, np.float32),)]
            return gen

        rd = PyReader(feed_list=[x], capacity=2,
                      use_double_buffer=False)
        rd.decorate_sample_list_generator(slow_epoch(1.0))
        rd.start()
        first = next(rd)
        np.testing.assert_allclose(np.asarray(first["rx"]),
                                   np.ones((1, 2)))
        old_thread = rd._thread
        rd.reset()
        assert rd._queue is None and rd._thread is None
        # the old fill thread must be stopped, not abandoned
        old_thread.join(timeout=5.0)
        assert not old_thread.is_alive()

        # epoch B: every batch must come from the NEW generator
        rd.decorate_sample_list_generator(slow_epoch(2.0, n=6))
        rd.start()
        got = []
        for item in iter(rd.next, None):
            got.append(float(np.asarray(item["rx"])[0, 0]))
            if len(got) == 6:
                break
        assert got == [2.0] * 6, f"stale epoch-A batches: {got}"
        rd.reset()

    def test_pyreader_host_mode_unchanged(self):
        _fresh()
        prog = fluid.Program()
        with fluid.program_guard(prog, fluid.Program()):
            x = fluid.layers.data("hx", shape=[4], dtype="float32")
        from paddle_tpu.reader import PyReader

        batches = [[(np.full(4, i, np.float32),)] for i in range(3)]
        rd = PyReader(feed_list=[x], capacity=4,
                      use_double_buffer=False)
        rd.decorate_sample_list_generator(lambda: iter(batches))
        got = list(rd)
        assert len(got) == 3
        assert isinstance(got[0]["hx"], np.ndarray)

    def test_prefetch_to_device_preserves_order_and_values(self):
        import jax

        from paddle_tpu.reader import prefetch_to_device

        feeds = ({"a": np.full((2, 2), i, np.float32)}
                 for i in range(6))
        out = list(prefetch_to_device(feeds, capacity=2))
        assert len(out) == 6
        for i, f in enumerate(out):
            assert isinstance(f["a"], jax.Array)
            np.testing.assert_allclose(np.asarray(f["a"]),
                                       np.full((2, 2), i))

    def test_prefetch_to_device_propagates_errors(self):
        from paddle_tpu.reader import prefetch_to_device

        def bad():
            yield {"a": np.zeros(2, np.float32)}
            raise RuntimeError("reader exploded")

        it = prefetch_to_device(bad(), capacity=1)
        next(it)
        with pytest.raises(RuntimeError, match="reader exploded"):
            list(it)

    def test_data_feeder_place_returns_device_arrays(self):
        import jax

        _fresh()
        prog = fluid.Program()
        with fluid.program_guard(prog, fluid.Program()):
            x = fluid.layers.data("fx", shape=[3], dtype="float32")
        feeder = fluid.DataFeeder([x], place=fluid.CPUPlace(),
                                  program=prog)
        feed = feeder.feed([(np.ones(3, np.float32),),
                            (np.zeros(3, np.float32),)])
        assert isinstance(feed["fx"], jax.Array)
        assert feed["fx"].shape == (2, 3)


class TestRunStepsDispatchWin:
    def test_scan_not_slower_than_sequential_on_cpu(self):
        """The CPU-measurable claim: amortizing K Python dispatches
        into one scan call must not LOSE to the sequential loop on a
        small config (it typically wins big; the bound here is loose
        so CI noise can't flake it).

        Measured as 3 INTERLEAVED (sequential, scan) leg pairs, best
        paired ratio: a single pass on this throttled 2-core host can
        land the two legs in different multi-second CPU-share windows
        and flake under full-lane contention (the PR 13 leftover;
        PERF.md measurement discipline — adjacent legs share a
        window)."""
        import time

        _fresh()
        prog, startup, loss = _mnist_fc()
        feed = _mnist_feeds(1, batch=8)[0]
        K = 30
        exe = fluid.Executor(fluid.CPUPlace())
        sc1 = fluid.Scope()
        exe.run(startup, scope=sc1)
        sc2 = fluid.Scope()
        exe2 = fluid.Executor(fluid.CPUPlace())
        exe2.run(startup, scope=sc2)

        def seq_leg():
            t0 = time.perf_counter()
            for _ in range(K):
                exe.run(prog, feed=feed, fetch_list=[loss],
                        scope=sc1, return_numpy=False)
            return time.perf_counter() - t0

        def scan_leg():
            t0 = time.perf_counter()
            exe2.run_steps(prog, feed=feed, fetch_list=[loss],
                           steps=K, scope=sc2, return_numpy=False)
            return time.perf_counter() - t0

        # warm both executables outside the timed windows (the scan
        # executable is specialized on K — warm with the SAME K)
        exe.run(prog, feed=feed, fetch_list=[loss], scope=sc1)
        exe2.run_steps(prog, feed=feed, fetch_list=[loss], steps=K,
                       scope=sc2)
        pairs = [(seq_leg(), scan_leg()) for _ in range(3)]
        assert exe2.last_run_steps_fallback is None
        # generous 2x guard on the BEST pair: the real measured ratio
        # is recorded in PERF.md ("Host dispatch & the multi-step
        # scan")
        best = min(sc / sq for sq, sc in pairs)
        assert best < 2.0, (
            f"run_steps scan regressed: best paired scan/seq ratio "
            f"{best:.2f} (pairs: "
            f"{[(round(sq, 3), round(sc, 3)) for sq, sc in pairs]})")
